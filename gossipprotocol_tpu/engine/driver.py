"""Experiment driver + convergence supervisor.

Replaces the reference's ``scheduler`` actor (``Program.fs:41-63``) and the
experiment-driver match block (``Program.fs:178-279``). The supervisor's
"count Alerts until counter = nodes" becomes the loop condition of a
``lax.while_loop``; the stopwatch around the whole run (``Program.fs:35,
194,54``) becomes a host-side wall-clock around the jitted rounds, with
compile time measured and excluded (reported separately — the reference
JIT-compiles nothing, so folding XLA compile into the metric would compare
apples to oranges).

The loop is *chunked*: one jitted call advances rounds until a runtime
``round_limit`` (or global convergence, whichever first), then the host
reads the converged count, emits a structured metrics record (SURVEY.md
§5.5), applies any scheduled fault injections (§5.3), and optionally
checkpoints (§5.4). The limit is ``min(next chunk boundary, max_rounds,
next scheduled fault)``, so fault rounds and round budgets are honored
exactly. State buffers are donated so the update stays in-place on device;
topology arrays, the PRNG key, and the limit are runtime arguments, so one
compiled executable serves every same-shape topology, seed, and budget.

The same host loop (`_drive`) drives both the single-chip and the
``shard_map`` engines — the engines only differ in how one chunk step is
issued.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gossipprotocol_tpu.protocols import (
    GossipState,
    PushSumState,
    gossip_done,
    gossip_init,
    pushsum_done,
    pushsum_init,
)
from gossipprotocol_tpu.protocols.gossip import gossip_round
from gossipprotocol_tpu.protocols.pushsum import pushsum_round, sum0
from gossipprotocol_tpu.protocols.sampling import device_topology
from gossipprotocol_tpu.topology.base import Topology

ALGORITHMS = ("gossip", "push-sum")


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Everything the reference reads from argv plus the knobs it hardcodes.

    ``semantics="reference"`` reproduces the reference's accidental rules
    (gossip threshold 11, push-sum streak-from-1 always-zero delta) for
    baseline curve matching; ``"intended"`` (default) implements the rules
    the README/report claim (SURVEY.md §2.4).
    """

    algorithm: str = "gossip"
    seed: int = 0
    threshold: int = 10            # gossip hits to converge (README.md:2)
    eps: float = 1e-10             # push-sum |Δ(s/w)| tolerance (Program.fs:116)
    streak_target: int = 3         # consecutive small-delta rounds (Program.fs:121)
    keep_alive: bool = True        # bulk-sync analogue of Actor2 (Program.fs:141-163)
    semantics: str = "intended"    # "intended" | "reference"
    alert_quorum: Optional[int] = None  # settled-node count that ends the
                                   # run (None = all). Renders the
                                   # reference's N+1 population converging
                                   # at N Alerts (Program.fs:169-176,53)
    predicate: str = "delta"       # push-sum: "delta" (reference-intended,
                                   # local) | "global" (sound; see pushsum.py)
    tol: float = 1e-4              # push-sum global-predicate tolerance
    edge_chunks: int = 1           # fanout-all delivery in K sequential
                                   # edge slices: K-fold smaller per-edge
                                   # intermediates (the 100M memory wall,
                                   # VERDICT r3 #3) for K kernel launches
    fanout: str = "one"            # push-sum sender: "one" (reference's
                                   # single-target send, Program.fs:128) |
                                   # "all" (diffusion; see diffusion.py)
    delivery: str = "scatter"      # push-sum fanout="one" delivery:
                                   # "scatter" (segment_sum) | "invert"
                                   # (receiver-side gather; measured 9x
                                   # SLOWER on TPU v5e — kept as a
                                   # validated negative result, see
                                   # README + pushsum.received_by_inversion)
    plan_cache: Optional[str] = None  # routed-delivery plan cache dir;
                                   # None = default ($GOSSIP_TPU_PLAN_CACHE
                                   # or ~/.cache/...), "none" = disabled.
                                   # NOT a trajectory field: a cache hit
                                   # loads bitwise the tables the build
                                   # produces (tests/test_routing.py)
    build_workers: Optional[int] = None  # processes for cold sharded-plan
                                   # builds; None = min(shards, cpus).
                                   # NOT a trajectory field: plans are
                                   # bitwise-identical across worker
                                   # counts (tests/test_routing.py), so
                                   # resume never depends on it
    routed_design: str = "push"    # sharded routed delivery: "push"
                                   # (owner-computes + all_to_all edge
                                   # shares, O(E/S + local_n) tables) |
                                   # "pull" (full-state all_gather +
                                   # O(n) plan_in — the escape hatch).
                                   # Single-chip routed runs ignore it.
                                   # NOT a trajectory field: both designs
                                   # are bitwise-equal to the single-chip
                                   # routed delivery
                                   # (tests/test_pushdelivery.py)
    rounds_per_kernel: int = 1     # K rounds fused into one pallas_call
                                   # (ops/megakernel.py): K=1 on
                                   # delivery='pallas' is the literal
                                   # per-round path; K>1 (or
                                   # delivery='megakernel') runs K-round
                                   # super-steps with convergence checked
                                   # in-kernel — the round count can
                                   # overshoot max_rounds/chunk bounds by
                                   # < K, never past convergence.
                                   # Trajectory field: K>1 changes the
                                   # compiled round granularity
    payload_wire: str = "f32"      # sharded edge-share slab wire dtype:
                                   # "f32" (bitwise default) | "bf16" |
                                   # "int8" (quantized on the wire, f32
                                   # accumulation — ops/sharddelivery.py).
                                   # Trajectory field: lossy wires change
                                   # the received sums
    exchange_overlap: bool = False # sharded push exchange on the
                                   # double-buffered DMA ring
                                   # (pallas_exchange overlap=True)
                                   # instead of start-all-then-wait. NOT
                                   # a trajectory field: the ring moves
                                   # the identical slab — bitwise-equal
                                   # transport (tests/test_pallasdelivery)
    value_mode: str = "scaled"     # push-sum init: "scaled" (i/N) | "index" (i)
    payload_dim: int = 1           # push-sum payload width d: 1 = the
                                   # scalar (s, w) protocol (bitwise the
                                   # pre-vector program); d > 1 rides an
                                   # [n, d] payload through the same
                                   # delivery plans (w stays per-node)
    workload: str = "avg"          # "avg" (plain averaging) | "sgp"
                                   # (Stochastic Gradient Push on a
                                   # synthetic least-squares shard per
                                   # node; learn/ package) | "gala"
                                   # (actor-learner groups: local SGP,
                                   # exact intra-group averaging, async
                                   # inter-group gossip; learn/gala.py)
    clock: str = "sync"            # activation clock (async_/ package):
                                   # "sync" (every node acts every round
                                   # — the pre-async engine, bitwise) |
                                   # "poisson" (per-node rate-r Poisson
                                   # clocks thinned to rounds: a node
                                   # sends iff its clock ticked).
                                   # Trajectory field
    activation_rate: float = 1.0   # poisson clock rate r: per-round
                                   # activation probability 1 - exp(-r).
                                   # Trajectory field (ignored when
                                   # clock='sync')
    groups: int = 1                # GALA learner-group count G (nodes
                                   # split into G contiguous id blocks).
                                   # Trajectory field (1 unless
                                   # workload='gala')
    accel: str = "off"             # push-sum fanout-all acceleration:
                                   # "off" | "chebyshev" (semi-iterative
                                   # weights, needs a spectral bound) |
                                   # "epd" (parameter-free two-buffer
                                   # scheme) — protocols/accel.py
    accel_lambda: Optional[float] = None  # Chebyshev γ = |λ₂(W)| bound in
                                   # (0, 1); None = host power-iteration
                                   # estimate at build time
    lr: float = 0.05               # SGP local gradient step size
    local_steps: int = 1           # SGP gradient steps per gossip round
    sgp_samples: int = 8           # SGP least-squares rows per node shard
    loss_tol: float = 1e-5         # SGP loss-plateau tolerance: converge
                                   # only when |Δ mean loss| <= loss_tol
                                   # on top of the consensus predicate
    dtype: Any = jnp.float32
    max_rounds: int = 1_000_000
    # rounds per jitted call / metrics cadence; None = auto-scale by node
    # count so one on-device chunk stays well under remote-execution
    # watchdogs (~minutes) while amortizing dispatch overhead
    chunk_rounds: Optional[int] = None
    seed_node: Optional[int] = None  # gossip start node; None = random (Program.fs:193)
    # aux subsystems
    metrics_callback: Optional[Callable[[dict], None]] = None
    checkpoint_every: int = 0      # chunks between checkpoints; 0 = off
    checkpoint_dir: Optional[str] = None
    fault_plan: Optional[dict] = None  # legacy {round:int -> node_ids}
                                   # kill sugar; merges into the schedule
    fault_schedule: Optional[Any] = None  # faults.FaultSchedule: timed
                                   # kill/revive strikes + link-loss
                                   # windows (utils/faults.py)
    repair: str = "off"            # overlay self-healing at strike
                                   # rounds: "off" | "prune" (drop dead
                                   # endpoints from the CSR) | "rewire"
                                   # (prune + deterministic degree-
                                   # preserving splice of survivors;
                                   # topology/repair.py). Trajectory
                                   # field: the policy rewrites the
                                   # adjacency mid-run
    event_plan: Optional[Any] = None  # events.EventPlan: timed edge
                                   # add/remove/swap events + optional
                                   # synthetic churn generator, executed
                                   # through the unified host-event
                                   # pipeline (events/). Trajectory
                                   # field (stored as its content
                                   # digest): the plan rewrites the
                                   # adjacency mid-run exactly like
                                   # repair does
    telemetry: Optional[Any] = None  # obs.Telemetry hub (None = off). Off
                                   # means *zero cost*: the compiled
                                   # programs are the ones this config
                                   # always built. On, the engines emit
                                   # spans/manifests and fold message
                                   # counters through the chunk scan —
                                   # counters ride a side buffer and
                                   # never feed back, so the state
                                   # trajectory stays bitwise identical
                                   # (tests/test_telemetry.py). NOT a
                                   # trajectory field for exactly that
                                   # reason
    round_budget: Optional[Any] = None  # None = unlimited; an int caps
                                   # the run at that many rounds with a
                                   # structured over_budget record;
                                   # "auto" derives the cap from the
                                   # analytic round prediction
                                   # (obs/predict.py) — requires a
                                   # predictable topology. NOT a
                                   # trajectory field: it only decides
                                   # when the host loop stops
    sweep: Optional[Any] = None    # sweep.SweepSpec: batch B lanes
                                   # (seeds/tolerances/rates) through ONE
                                   # compiled chunk program via vmapped
                                   # stacked state (sweep/engine.py).
                                   # None = the ordinary single-run
                                   # engines. Lane i is bitwise the
                                   # standalone run with lane i's config,
                                   # so this is not a trajectory field —
                                   # it is B trajectories
    sentinel: str = "off"          # on-device health sentinel: "off"
                                   # (zero cost — the compiled chunk is
                                   # byte-identical to the pre-sentinel
                                   # program) | "on" (detect + record
                                   # only) | "quarantine" (kill + zero
                                   # offending rows through the host-
                                   # event pipeline) | "rollback"
                                   # (quarantine + restore the newest
                                   # checkpoint predating the trip and
                                   # replay). NOT a trajectory field:
                                   # like telemetry it only observes —
                                   # the quarantines it performs are
                                   # persisted in checkpoint metadata
    quarantine_log: Tuple = ()     # ((round, (ids...)), ...) quarantines
                                   # a resumed checkpoint lived through
                                   # (from its "quarantines" metadata) —
                                   # replayed into the adjacency exactly
                                   # like scheduled kills. Populated by
                                   # the resume path, not by users

    @property
    def schedule(self):
        """The effective :class:`~gossipprotocol_tpu.utils.faults.
        FaultSchedule` — ``fault_schedule`` with the legacy ``fault_plan``
        kills merged in. Always a schedule object (possibly empty), so
        call sites test ``sched.has_strikes`` / ``sched.has_loss``
        instead of juggling two optional fields."""
        from gossipprotocol_tpu.utils import faults

        return faults.as_schedule(self.fault_schedule, self.fault_plan)

    @property
    def events(self):
        """The effective :class:`~gossipprotocol_tpu.events.plan.
        EventPlan` — always a plan object (possibly empty), so call
        sites test ``plan.has_events`` instead of None-checking."""
        from gossipprotocol_tpu.events import plan as events_plan

        return events_plan.as_plan(self.event_plan)

    def __post_init__(self):
        if self.algorithm not in ALGORITHMS:
            raise ValueError(
                f"unknown algorithm {self.algorithm!r}; valid: {ALGORITHMS} "
                "(the reference prints 'option invalid', Program.fs:207)"
            )
        if self.semantics not in ("intended", "reference"):
            raise ValueError("semantics must be 'intended' or 'reference'")
        if self.predicate not in ("delta", "global"):
            raise ValueError("predicate must be 'delta' or 'global'")
        if self.predicate == "global" and self.semantics == "reference":
            raise ValueError(
                "predicate='global' is incompatible with semantics='reference' "
                "(the reference's accidental rule ignores the estimate entirely)"
            )
        if self.alert_quorum is not None and self.alert_quorum < 1:
            raise ValueError("alert_quorum must be >= 1")
        if self.fanout not in ("one", "all"):
            raise ValueError("fanout must be 'one' or 'all'")
        if self.edge_chunks < 1:
            raise ValueError("edge_chunks must be >= 1")
        if self.edge_chunks > 1 and not (
            self.algorithm == "push-sum" and self.fanout == "all"
        ):
            raise ValueError(
                "edge_chunks applies to fanout-all diffusion only (the "
                "other senders have no per-edge intermediates to slice)"
            )
        if self.edge_chunks > 1 and self.delivery in (
            "routed", "pallas", "megakernel"
        ):
            raise ValueError(
                "edge_chunks applies to the scatter delivery; the routed "
                "and pallas plans stream at fixed memory already"
            )
        if self.fanout == "all" and self.semantics == "reference":
            raise ValueError(
                "fanout='all' is incompatible with semantics='reference': the "
                "single-target send IS the reference's accidental behavior "
                "(Program.fs:128) that the diffusion variant replaces"
            )
        if self.delivery not in ("scatter", "invert", "routed", "pallas",
                                 "megakernel"):
            raise ValueError("delivery must be 'scatter', 'invert', "
                             "'routed', 'pallas', or 'megakernel'")
        sched = self.schedule.validate()  # structural check, loud + early
        from gossipprotocol_tpu.topology.repair import validate_policy

        validate_policy(self.repair)
        # structural plan check (id-range validation needs the node count
        # and runs at engine entry, where the topology is known)
        plan = self.events.validate()
        if plan and self.semantics == "reference":
            raise ValueError(
                "event plans rewrite the adjacency mid-run; "
                "semantics='reference' replays the F# baseline's static "
                "world and rejects topology schedules"
            )
        if self.repair != "off" and self.semantics == "reference":
            raise ValueError(
                "repair applies to faulted runs; semantics='reference' "
                "rejects fault schedules entirely, so there is nothing "
                "to repair"
            )
        # half-configured checkpointing silently disables itself in the
        # drive loop (checkpointing = every AND dir); that silence has
        # cost users their recovery story, so be loud at config time
        if bool(self.checkpoint_every) != bool(self.checkpoint_dir):
            import warnings

            missing = ("checkpoint_dir" if self.checkpoint_every
                       else "checkpoint_every")
            given = ("checkpoint_every" if self.checkpoint_every
                     else "checkpoint_dir")
            warnings.warn(
                f"checkpointing is DISABLED: {given} is set but {missing} "
                "is not — both are required, no checkpoint will be "
                "written this run",
                stacklevel=2,
            )
        if self.delivery in ("routed", "pallas", "megakernel"):
            # pallas shares the routed contract exactly: it is the same
            # plan geometry with the copy chain fused into gather
            # kernels (ops/pallasdelivery.py), held bitwise equal;
            # megakernel is the pallas geometry with K rounds looped
            # inside one kernel (ops/megakernel.py)
            if self.algorithm != "push-sum" or self.fanout != "all":
                raise ValueError(
                    f"delivery='{self.delivery}' applies to fanout-all "
                    "diffusion only (the static edge structure is what "
                    "the routing plan compiles; single-target draws "
                    "fresh targets every round — see README "
                    "'Performance')"
                )
            # kill/revive strikes are fine: the driver's kill_disconnected
            # keeps the dead set well-defined and the routed round's
            # live-degree general path (diffusion.py) stays exact under
            # any dead set. Loss is not: the plan's pair tables are
            # compiled once and cannot thread a fresh per-edge mask.
            if sched.has_loss:
                raise ValueError(
                    f"delivery='{self.delivery}' compiles a static "
                    "routing plan and cannot apply per-edge drop masks "
                    "through it; use delivery='scatter' for loss windows"
                )
            if jnp.dtype(self.dtype) != jnp.float32:
                raise ValueError(
                    f"delivery='{self.delivery}' routes f32 lane pairs; "
                    "use delivery='scatter' for float64 runs"
                )
        if self.delivery == "pallas" and self.routed_design == "pull":
            raise ValueError(
                "delivery='pallas' shards with the push design only "
                "(the async remote-copy exchange replaces the push "
                "path's all_to_all; pull has no edge-share exchange "
                "to replace) — drop routed_design='pull'"
            )
        if self.routed_design not in ("push", "pull"):
            raise ValueError("routed_design must be 'push' or 'pull'")
        if self.rounds_per_kernel < 1:
            raise ValueError("rounds_per_kernel must be >= 1")
        if self.rounds_per_kernel > 1 and self.delivery not in (
            "pallas", "megakernel"
        ):
            raise ValueError(
                "rounds_per_kernel > 1 loops rounds inside the fused "
                "Pallas kernel — it requires delivery='pallas' (or "
                "'megakernel'); the other deliveries dispatch one round "
                "per launch by construction"
            )
        if self.delivery == "megakernel" or self.rounds_per_kernel > 1:
            # the in-kernel round loop replays the all-alive synchronous
            # scalar round only: everything the kernel would have to
            # re-derive per round (activation draws, loss masks, payload
            # loops, learner steps, mid-run adjacency rewrites) stays on
            # the per-round paths
            if self.clock != "sync":
                raise ValueError(
                    "the round-loop megakernel replays the synchronous "
                    "round in-register; poisson activation draws fresh "
                    "masks per round — use clock='sync' or "
                    "rounds_per_kernel=1"
                )
            if self.payload_dim != 1:
                raise ValueError(
                    "the round-loop megakernel carries the scalar (s, w) "
                    "state in VMEM; vector payloads need the per-round "
                    "pallas path — use delivery='pallas' with "
                    "rounds_per_kernel=1"
                )
            if self.workload != "avg":
                raise ValueError(
                    "the round-loop megakernel fuses the plain averaging "
                    "round; SGP/GALA inject gradient mass between rounds "
                    "— use delivery='pallas' with rounds_per_kernel=1"
                )
            if sched or plan or self.repair != "off":
                raise ValueError(
                    "the round-loop megakernel compiles K rounds against "
                    "a fixed live topology; fault strikes, loss windows, "
                    "topology events and repair all need the per-round "
                    "engine — use delivery='pallas' with "
                    "rounds_per_kernel=1"
                )
            if (self.chunk_rounds is not None
                    and self.chunk_rounds % self.rounds_per_kernel):
                raise ValueError(
                    f"chunk_rounds ({self.chunk_rounds}) must be a "
                    f"multiple of rounds_per_kernel "
                    f"({self.rounds_per_kernel}) so chunk boundaries "
                    "land on super-step boundaries"
                )
        if self.payload_wire not in ("f32", "bf16", "int8"):
            raise ValueError(
                "payload_wire must be 'f32', 'bf16', or 'int8'")
        if self.payload_wire != "f32":
            if self.delivery not in ("routed", "pallas"):
                raise ValueError(
                    "payload_wire compresses the sharded push-design "
                    "edge-share slab; it requires delivery='routed' or "
                    "'pallas' (the scatter paths ship no slab, and the "
                    "megakernel is single-chip)"
                )
            if self.routed_design != "push":
                raise ValueError(
                    "payload_wire compresses the push design's edge-share "
                    "exchange; the pull design all-gathers full state "
                    "vectors instead — drop routed_design='pull'"
                )
        if self.exchange_overlap:
            if self.delivery not in ("routed", "pallas"):
                raise ValueError(
                    "exchange_overlap schedules the sharded push-design "
                    "exchange on the double-buffered DMA ring; it "
                    "requires delivery='routed' or 'pallas'"
                )
            if self.routed_design != "push":
                raise ValueError(
                    "exchange_overlap replaces the push design's "
                    "edge-share exchange; the pull design has none — "
                    "drop routed_design='pull'"
                )
        if self.delivery == "invert":
            if self.algorithm != "push-sum" or self.fanout != "one":
                raise ValueError(
                    "delivery='invert' applies to single-target push-sum "
                    "only (gossip picks its inverted delivery automatically; "
                    "diffusion walks every edge and has nothing to invert)"
                )
            if sched or plan:
                raise ValueError(
                    "delivery='invert' is exact only while no node can die "
                    "mid-run, every send lands, and the adjacency never "
                    "changes (receivers recompute senders' draws against "
                    "the compiled graph); drop the fault schedule / event "
                    "plan or use delivery='scatter'"
                )
        if self.payload_dim < 1:
            raise ValueError("payload_dim must be >= 1")
        if self.payload_dim > 1:
            if self.algorithm != "push-sum" or self.semantics == "reference":
                raise ValueError(
                    "payload_dim > 1 rides push-sum's (s, w) state under "
                    "intended semantics; gossip and the reference replay "
                    "are scalar protocols"
                )
            if self.delivery == "invert":
                raise ValueError(
                    "delivery='invert' recomputes senders' scalar draws "
                    "and is scalar-payload only; use 'scatter' or 'routed' "
                    "for payload_dim > 1"
                )
        if self.workload not in ("avg", "sgp", "gala"):
            raise ValueError("workload must be 'avg', 'sgp', or 'gala'")
        if self.clock not in ("sync", "poisson"):
            raise ValueError("clock must be 'sync' or 'poisson'")
        if self.activation_rate <= 0:
            raise ValueError(
                "activation_rate is a Poisson clock rate and must be > 0"
            )
        if self.clock == "poisson":
            if self.accel != "off":
                raise ValueError(
                    "clock='poisson' gates senders per round; the "
                    "accelerated schemes assume the *fixed* mixing matrix "
                    "W every iteration — run them under clock='sync'"
                )
            if self.semantics == "reference":
                raise ValueError(
                    "clock='poisson' models continuous-time activation; "
                    "semantics='reference' replays the F# baseline's "
                    "synchronous accident and must stay clock='sync'"
                )
            if self.delivery == "invert":
                raise ValueError(
                    "delivery='invert' reconstructs deliveries assuming "
                    "every eligible sender sent; a poisson clock idles "
                    "senders every round — use delivery='scatter'"
                )
        if self.groups < 1:
            raise ValueError("groups must be >= 1")
        if self.groups > 1 and self.workload != "gala":
            raise ValueError(
                "groups partitions nodes into GALA learner groups; it "
                "requires workload='gala'"
            )
        if self.workload == "gala":
            if self.groups < 2:
                raise ValueError(
                    "workload='gala' needs at least 2 learner groups "
                    "(groups=1 is plain SGP — use workload='sgp')"
                )
            if self.algorithm != "push-sum" or self.semantics == "reference":
                raise ValueError(
                    "workload='gala' mixes between groups by push-sum "
                    "gossip: it requires algorithm='push-sum' with "
                    "intended semantics"
                )
            if self.predicate != "global":
                raise ValueError(
                    "workload='gala' certifies inter-group consensus, "
                    "which is the 'global' predicate"
                )
            if self.accel != "off":
                raise ValueError(
                    "workload='gala' re-injects mass every round (local "
                    "gradient steps + group averaging); the accelerated "
                    "schemes assume a fixed linear iteration"
                )
            if self.delivery != "scatter":
                raise ValueError(
                    "workload='gala' supports delivery='scatter' (same "
                    "contract as workload='sgp')"
                )
            if sched or plan:
                raise ValueError(
                    "workload='gala' keeps groups exactly synchronized "
                    "by intra-group averaging; fault strikes, loss "
                    "windows and topology events are not modeled for it "
                    "yet — drop the fault schedule / event plan"
                )
        if self.accel not in ("off", "chebyshev", "epd"):
            raise ValueError("accel must be 'off', 'chebyshev', or 'epd'")
        if self.lr <= 0:
            raise ValueError("lr must be > 0")
        if self.local_steps < 1:
            raise ValueError("local_steps must be >= 1")
        if self.sgp_samples < 1:
            raise ValueError("sgp_samples must be >= 1")
        if self.loss_tol <= 0:
            raise ValueError("loss_tol must be > 0")
        if self.workload == "sgp":
            if self.algorithm != "push-sum" or self.semantics == "reference":
                raise ValueError(
                    "workload='sgp' is Stochastic Gradient *Push*: it "
                    "requires algorithm='push-sum' with intended semantics"
                )
            if self.predicate != "global":
                raise ValueError(
                    "workload='sgp' certifies consensus distance, which is "
                    "the 'global' predicate; the local 'delta' rule would "
                    "fire while gradients still move the mean"
                )
            if self.accel != "off":
                raise ValueError(
                    "workload='sgp' re-injects mass every round (gradient "
                    "steps); the accelerated two-buffer schemes assume a "
                    "fixed linear iteration — run them on workload='avg'"
                )
            if self.delivery not in ("scatter", "routed", "pallas"):
                raise ValueError(
                    "workload='sgp' supports delivery='scatter', "
                    "'routed', or 'pallas' (the fanout-all plans ride "
                    "the d-dim payload through matvec_payload; invert is "
                    "scalar-only and the megakernel fuses the scalar "
                    "averaging round)"
                )
        if self.accel != "off":
            if self.algorithm != "push-sum" or self.fanout != "all":
                raise ValueError(
                    "accel applies to fanout-all diffusion push-sum: the "
                    "polynomial schemes accelerate a fixed mixing matrix W, "
                    "which only the diffusion sender applies"
                )
            if self.delivery != "scatter":
                raise ValueError(
                    "accel currently runs on delivery='scatter' (the "
                    "two-buffer combination wraps the scatter diffusion "
                    "mix)"
                )
            if sched:
                raise ValueError(
                    "accel assumes a *fixed* mixing matrix: Chebyshev/EPD "
                    "coefficient schedules are invalid the moment a strike "
                    "or loss window rewrites W mid-run; drop the fault "
                    "schedule or use accel='off'"
                )
            if self.repair != "off":
                raise ValueError(
                    "accel assumes a fixed mixing matrix; repair rewrites "
                    "the adjacency mid-run"
                )
            if plan:
                raise ValueError(
                    "accel assumes a fixed mixing matrix; an event plan "
                    "rewrites the adjacency mid-run"
                )
        if self.accel_lambda is not None and not (
            0.0 < self.accel_lambda < 1.0
        ):
            raise ValueError(
                "accel_lambda is a spectral bound γ = |λ₂(W)| and must lie "
                "strictly in (0, 1)"
            )
        if self.round_budget is not None and self.round_budget != "auto":
            if not isinstance(self.round_budget, int) or isinstance(
                self.round_budget, bool
            ) or self.round_budget < 1:
                raise ValueError(
                    "round_budget must be None, a positive int, or 'auto'"
                )
        if self.sentinel not in ("off", "on", "quarantine", "rollback"):
            raise ValueError(
                "sentinel must be 'off', 'on', 'quarantine', or 'rollback'"
            )
        if plan.value_faults:
            if self.algorithm != "push-sum":
                raise ValueError(
                    "value faults corrupt push-sum's (s, w) payload; "
                    "gossip carries no numeric mass to poison — use "
                    "algorithm='push-sum'"
                )
            # reference semantics / megakernel / invert / accel / gala
            # already reject any non-empty plan above — the matrix entries
            # for value-fault plans fall out of those checks
        if self.sentinel != "off":
            if self.algorithm != "push-sum":
                raise ValueError(
                    "the health sentinel checks push-sum's (s, w) mass "
                    "invariants; gossip has none — use "
                    "algorithm='push-sum'"
                )
            if self.semantics == "reference":
                raise ValueError(
                    "the sentinel guards the mass-conserving push-sum "
                    "state; semantics='reference' replays the F# walk "
                    "and rejects it"
                )
            if self.delivery == "megakernel" or self.rounds_per_kernel > 1:
                raise ValueError(
                    "the sentinel folds its health check through the "
                    "per-round chunk loop; the round-loop megakernel "
                    "checks nothing between its fused rounds — use "
                    "delivery='pallas' with rounds_per_kernel=1"
                )
            if self.sweep is not None:
                raise ValueError(
                    "the sentinel quarantines through the host-event "
                    "pipeline, which the vmapped sweep lanes do not "
                    "carry — run sentinel runs unswept"
                )
            if self.accel != "off" and self.sentinel in ("quarantine",
                                                         "rollback"):
                raise ValueError(
                    "sentinel quarantine kills nodes mid-run; accel "
                    "assumes a fixed mixing matrix — use sentinel='on' "
                    "for detection only"
                )
        if self.sentinel == "rollback" and not (
            self.checkpoint_every and self.checkpoint_dir
        ):
            raise ValueError(
                "sentinel='rollback' restores the newest checkpoint "
                "predating a trip; it requires checkpoint_every AND "
                "checkpoint_dir"
            )

    def resolve_chunk_rounds(
        self, num_nodes: int, num_edges: Optional[int] = None
    ) -> int:
        """Auto chunk size: target ~30 s of on-device work per chunk,
        clamped to [4, 4096] — or [1, 4096] when a single round already
        exceeds ~15 s, since then even the 4-round dispatch-amortization
        floor would bust the remote watchdog's single-dispatch budget
        (measured ~90 s on the axon rig; exceeding it crashes the TPU
        worker, observed twice plus once under a controlled probe).

        The per-round cost model uses measured v5e worst-case rates
        (README roofline): ~100 ns/node for the node-sharded senders
        (covers the scatter paths with margin), plus ~65 ns/edge for
        fanout-all diffusion, whose rounds walk every edge — at 10M
        power-law that is ~5.4 s/round, so a node-count-only estimate
        would pick ~170 s chunks and kill the worker. float64 divides
        the budget by 16 (TPU f64 is software-emulated, ~10-30x slower).
        """
        if self.chunk_rounds is not None:
            return self.chunk_rounds
        per_round_s = max(num_nodes, 1) * 100e-9
        if self.algorithm == "push-sum" and self.fanout == "all":
            # routed delivery replaces the per-edge random scatter with
            # stream-speed routing passes (measured ~6 ns/pair + class
            # overhead, experiments/route_bench.py); pallas fuses those
            # passes into single gathers — budget it the same, erring
            # toward smaller chunks
            per_edge = (12e-9 if self.delivery in ("routed", "pallas",
                                                   "megakernel")
                        else 65e-9)
            per_round_s += (num_edges or 0) * per_edge
        if jnp.dtype(self.dtype) == jnp.float64:
            per_round_s *= 16
        # the >=4 floor only amortizes dispatch overhead; when single
        # rounds are already tens of seconds (f64 diffusion at 10M), a
        # forced 4-round chunk would itself bust the watchdog — drop to
        # single-round chunks instead
        lo = 1 if per_round_s > 15.0 else 4
        chunk = max(lo, min(4096, int(30.0 / per_round_s)))
        if self.rounds_per_kernel > 1:
            # chunk boundaries land on super-step boundaries (explicit
            # chunk_rounds is validated for this; the auto pick rounds up)
            k = self.rounds_per_kernel
            chunk = -(-chunk // k) * k
        return chunk


@dataclasses.dataclass
class RunResult:
    converged: bool
    rounds: int
    wall_ms: float            # convergence time, excluding compile
    compile_ms: float
    num_nodes: int
    algorithm: str
    final_state: Any
    metrics: List[dict]
    checkpoints: List[str] = dataclasses.field(default_factory=list)
    # "drain" when an installed stop check (install_stop_check) ended the
    # run early at a chunk boundary — checkpoint saved, not a convergence
    # verdict. None for every normally-finished run.
    stopped: Optional[str] = None

    @property
    def estimate_error(self) -> Optional[float]:
        """Push-sum: max |s/w − achievable mean| over healthy nodes.

        The reference mean is computed over *healthy* rows only: a dead
        node's mass is stranded (SURVEY.md §5.3 semantics), so the mean the
        survivors can reach is sum_alive(s)/sum_alive(w).

        Only meaningful on *connected* topologies: push-sum provably
        averages within each connected component, so on a graph with
        stragglers (e.g. sparse Erdős–Rényi with isolated pairs) this
        reports the gap between component means, not a protocol error.
        """
        st = self.final_state
        if not hasattr(st, "ratio"):  # PushSumState or the WalkState
            return None
        ratio = np.asarray(st.ratio, dtype=np.float64)
        alive = np.asarray(st.alive)
        if not alive.any():
            return None
        # axis=0 keeps this exact for vector payloads: s is [k] or [k, d],
        # the sum is a scalar or per-dimension [d] mean respectively
        s = np.asarray(st.s, np.float64)[alive].sum(axis=0)
        w = np.asarray(st.w, np.float64)[alive].sum()
        if hasattr(st, "msg_s"):
            # the walk's in-flight token carries real mass (its holder is
            # always an alive node); the reachable mean includes it
            s = s + float(st.msg_s)
            w += float(st.msg_w)
        true_mean = s / w
        return float(np.abs(ratio[alive] - true_mean).max())


def pick_seed_node(num_nodes: int, seed: int, alive=None) -> int:
    """Random gossip start node (reference: ``Random().Next(0, nodes)``,
    ``Program.fs:193``) — derived from the run seed, reproducible.

    ``alive`` (bool mask or None): when the uniform pick lands on a
    birth-excluded node, redraw among the alive ones — planting the rumor
    in a minority component would stall the whole run while the majority
    is healthy. One derivation owns the ``seed ^ 0x5EED`` stream so the
    single-chip and sharded engines can never drift apart on it.
    """
    rng = np.random.default_rng(seed ^ 0x5EED)
    node = int(rng.integers(0, num_nodes))
    if alive is not None and not bool(alive[node]):
        alive_ids = np.flatnonzero(alive)
        if alive_ids.size:
            node = int(rng.choice(alive_ids))
    return node


def initial_alive(topo: Topology) -> Optional[jax.Array]:
    """Healthy-at-birth mask: only the largest connected component.

    Sparse random graphs are born with isolated nodes *and* small
    components (ER(8)@10M: ~3350 degree-0 nodes and a handful of isolated
    pairs/triples). Neither can ever agree with the majority — the rumor
    cannot reach them, and push-sum averages per component — so they are
    excluded from the supervisor's predicate up front, the same mechanism
    as fault-injected nodes (majority-partition semantics,
    :func:`gossipprotocol_tpu.utils.faults.kill_disconnected`; computed
    and cached by :meth:`Topology.birth_alive`).
    None = everyone healthy."""
    alive = topo.birth_alive()
    return None if alive is None else jnp.asarray(alive)


def use_megakernel(cfg: RunConfig) -> bool:
    """Does this config run the K-round fused kernel
    (ops/megakernel.py)? ``--delivery megakernel`` always; the pallas
    path joins it when ``--rounds-per-kernel`` exceeds 1. K=1 on
    ``--delivery pallas`` stays the literal per-round program (the one
    the goldens pin)."""
    return cfg.delivery == "megakernel" or (
        cfg.delivery == "pallas" and cfg.rounds_per_kernel > 1
    )


def build_protocol(
    topo: Topology,
    cfg: RunConfig,
    num_rows: Optional[int] = None,
    allow_all_alive: bool = True,
):
    """(init_state, round_core(state, nbrs, key, ...), done_fn, extra_stats,
    (all_alive, targets_alive)).

    The returned flag pair is the single source of truth for the liveness
    fast paths — the sharded engine reuses it rather than re-deriving
    eligibility with its own formula.

    ``num_rows`` > num_nodes pads the state with phantom rows (dead and
    converged — invisible to the protocol and the predicate) for sharding.
    ``extra_stats`` (or None) adds protocol-specific scalars to the chunk
    stats — gossip reports its spreader count for stall detection.

    When no node can ever die — no fault plan, no birth exclusions, no
    padding rows — the round compiles with the aliveness masks removed
    (``all_alive``), dropping a full-length random gather from push-sum
    (~29 % of the round at 10M nodes). ``allow_all_alive=False`` forces
    the general path: required when resuming a checkpoint that already
    carries dead nodes.
    """
    ref = cfg.semantics == "reference"
    n = topo.num_nodes
    rows = num_rows or n
    alive0 = initial_alive(topo)
    sched = cfg.schedule
    # only aliveness-*changing* events (kills/revives) disable the static
    # liveness fast paths; loss windows drop messages without ever
    # touching the alive mask, so a drop-only schedule keeps both flags
    strikes = sched.has_strikes
    loss_windows = sched.static_loss_windows()
    # () under clock='sync': every round core treats the empty spec as
    # "trace the literal synchronous program", so sync runs compile to
    # the byte-identical pre-async jaxpr (pinned by the program goldens)
    clock = run_clock_spec(topo, cfg)
    all_alive = (
        allow_all_alive and not strikes and alive0 is None and rows == n
    )
    # birth exclusions are whole components, so an alive node's neighbors
    # are alive: the target-liveness gather can go as long as no fault
    # strike (or resumed dead set) can make the dead set component-open
    targets_alive = allow_all_alive and not strikes
    if cfg.algorithm == "gossip":
        if cfg.seed_node is not None:
            seed_node = cfg.seed_node  # explicit: honored even if dead
        else:
            seed_node = pick_seed_node(n, cfg.seed, alive=topo.birth_alive())
        # reference converges on the 11th hearing (Program.fs:91-92); the
        # intended rule is 10 (README.md:2)
        threshold = cfg.threshold + 1 if ref else cfg.threshold
        state = gossip_init(rows, seed_node)
        keep_alive = effective_keep_alive(topo, cfg)
        core = partial(
            gossip_round, n=n, threshold=threshold, keep_alive=keep_alive,
            all_alive=all_alive, inverted=gossip_inversion_enabled(topo, cfg),
            loss_windows=loss_windows, clock=clock,
        )
        done_fn = gossip_done
        extra_stats = lambda s: {  # noqa: E731
            "spreading": gossip_spreading_count(s, keep_alive)
        }
    else:
        if not ref or cfg.fanout == "all":
            # (the walk branch below builds its own WalkState; fanout=all
            # + reference is rejected by RunConfig, so this condition is
            # exactly "not the walk")
            state = pushsum_init(
                rows, value_mode=cfg.value_mode, dtype=cfg.dtype,
                reference_semantics=ref, real_nodes=n,
                payload_dim=cfg.payload_dim,
            )
        if cfg.accel != "off":
            from gossipprotocol_tpu.protocols.accel import (
                accel_init,
                accel_round,
                estimate_gamma,
            )

            state = accel_init(
                rows, value_mode=cfg.value_mode, dtype=cfg.dtype,
                real_nodes=n, payload_dim=cfg.payload_dim,
            )
            gamma = 0.0
            if cfg.accel == "chebyshev":
                gamma = (cfg.accel_lambda if cfg.accel_lambda is not None
                         else estimate_gamma(topo))
            core = partial(
                accel_round,
                n=n,
                variant=cfg.accel,
                gamma=float(gamma),
                eps=cfg.eps,
                streak_target=cfg.streak_target,
                predicate=cfg.predicate,
                tol=cfg.tol,
                all_alive=all_alive,
                targets_alive=targets_alive,
                edge_chunks=cfg.edge_chunks,
            )
        elif cfg.fanout == "all":
            from gossipprotocol_tpu.protocols.diffusion import (
                pushsum_diffusion_round,
                pushsum_diffusion_round_routed,
            )

            if loss_windows and topo.implicit_full:
                raise ValueError(
                    "per-edge loss windows need an explicit edge list; "
                    "the implicit complete graph's diffusion is two "
                    "reductions with no edges to mask — materialize the "
                    "topology or drop the loss windows"
                )
            if use_megakernel(cfg):
                # K-round super-steps fused into one pallas_call: the
                # kernel replays the all-alive routed round in-register,
                # checking convergence between rounds so a super-step
                # never runs past the supervisor predicate
                from gossipprotocol_tpu.ops.megakernel import (
                    make_megakernel_round,
                )

                if not all_alive:
                    raise ValueError(
                        "the round-loop megakernel compiles the all-alive "
                        "round only; this run carries dead or padded rows "
                        "(birth exclusions, a resumed dead set, or "
                        "sharding) — use delivery='pallas' with "
                        "rounds_per_kernel=1"
                    )
                core = make_megakernel_round(
                    n=n,
                    rounds_per_kernel=max(cfg.rounds_per_kernel, 1),
                    eps=cfg.eps,
                    streak_target=cfg.streak_target,
                    predicate=cfg.predicate,
                    tol=cfg.tol,
                    quorum=cfg.alert_quorum,
                    interpret=(default_platform() != "tpu"),
                )
            else:
                # pallas rides the routed round unchanged: the delivery
                # pytree (RoutedDelivery vs PallasDelivery) carries the
                # kernels; the round only calls .matvec/.degree
                round_fn = (pushsum_diffusion_round_routed
                            if cfg.delivery in ("routed", "pallas")
                            else pushsum_diffusion_round)
                core = partial(
                    round_fn,
                    n=n,
                    eps=cfg.eps,
                    streak_target=cfg.streak_target,
                    predicate=cfg.predicate,
                    tol=cfg.tol,
                    all_alive=all_alive,
                    targets_alive=targets_alive,
                    clock=clock,
                )
                if cfg.delivery not in ("routed", "pallas"):
                    # routed runs never carry loss (RunConfig rejects
                    # it); the scatter round threads the drop windows
                    # through delivery
                    core = partial(core, loss_windows=loss_windows)
                    if cfg.edge_chunks > 1:
                        core = partial(core, edge_chunks=cfg.edge_chunks)
                else:
                    core = partial(
                        core, interpret=(default_platform() != "tpu"))
        elif ref:
            # the reference's actual dynamics: a single-token random walk
            # (one MainPushSum in flight, Program.fs:128; SURVEY §2.4.2).
            # One engine round = one hop, so `rounds` is a hop count
            # cross-validated against native.async_pushsum_hops.
            from gossipprotocol_tpu.protocols.walk import (
                pushsum_walk_init,
                pushsum_walk_round,
            )

            if rows != n:
                raise ValueError(
                    "semantics='reference' push-sum is the single-token "
                    "walk — a serial process that cannot shard; run it "
                    "single-chip (the reference is single-process, "
                    "Program.fs:36)"
                )
            if sched:
                raise ValueError(
                    "semantics='reference' push-sum cannot take faults or "
                    "loss: killing the token holder — or dropping the one "
                    "in-flight message — hangs the walk exactly as an "
                    "actor crash would hang the reference (SURVEY §5.3)"
                )
            if cfg.delivery != "scatter":
                raise ValueError(
                    "delivery variants invert/route the all-send "
                    "deliveries; reference push-sum is the single-token "
                    "walk and has nothing to invert — drop --delivery"
                )
            if cfg.seed_node is not None:
                seed_node = cfg.seed_node
                birth = topo.birth_alive()
                if (not topo.implicit_full
                        and int(topo.degree[seed_node]) == 0) or (
                        birth is not None and not bool(birth[seed_node])):
                    raise ValueError(
                        f"seed node {seed_node} has no neighbors or sits "
                        "in a birth-excluded minority component — the "
                        "walk would be trapped there forever (the "
                        "reference would hang identically)"
                    )
            else:
                # birth mask = giant component, where every node has a
                # neighbor and neighbors stay in-component: the walk can
                # never trap from a default start
                seed_node = pick_seed_node(n, cfg.seed,
                                           alive=topo.birth_alive())
            state = pushsum_walk_init(
                n, seed_node, value_mode=cfg.value_mode, dtype=cfg.dtype)
            core = partial(
                pushsum_walk_round, n=n, streak_target=cfg.streak_target)
        else:
            if cfg.delivery == "invert":
                # loud config errors, not silent fallbacks (SURVEY.md §5.6)
                require_invertible(topo)
                if not targets_alive:
                    raise ValueError(
                        "delivery='invert' is exact only while the dead set "
                        "is component-closed (no fault plan, no resumed "
                        "arbitrary dead set) — use delivery='scatter'"
                    )
            core = partial(
                pushsum_round,
                n=n,
                eps=cfg.eps,
                streak_target=cfg.streak_target,
                reference_semantics=ref,
                predicate=cfg.predicate,
                tol=cfg.tol,
                all_alive=all_alive,
                targets_alive=targets_alive,
                delivery=cfg.delivery,
                loss_windows=loss_windows,
                clock=clock,
            )
        if cfg.workload in ("sgp", "gala"):
            from gossipprotocol_tpu.learn import (
                make_gala_core, make_sgp_core, sgp_init,
            )

            # the mixing core above is reused verbatim; only the state
            # swaps (x₀ = 0 plus the loss scalar) and the round gains the
            # local gradient step + loss-plateau gate. The SGPBundle data
            # rides the nbrs slot — see device_arrays.
            state = sgp_init(
                rows, cfg.payload_dim, dtype=cfg.dtype, real_nodes=n)
            if cfg.workload == "sgp":
                core = make_sgp_core(
                    core, lr=cfg.lr, local_steps=cfg.local_steps,
                    loss_tol=cfg.loss_tol,
                )
            else:
                # GALA rides the SGP chassis: same state, same bundle,
                # plus the intra-group exact average before the mix
                if n % cfg.groups:
                    raise ValueError(
                        f"workload='gala' splits {n} nodes into "
                        f"{cfg.groups} equal groups — nodes must be "
                        "divisible by groups"
                    )
                core = make_gala_core(
                    core, num_groups=cfg.groups,
                    group_size=n // cfg.groups, lr=cfg.lr,
                    local_steps=cfg.local_steps, loss_tol=cfg.loss_tol,
                )
        done_fn = pushsum_done
        extra_stats = None
        if cfg.workload in ("sgp", "gala"):
            extra_stats = lambda s: {"train_loss": s.loss}  # noqa: E731

    if alive0 is not None:
        if rows > n:
            alive0 = jnp.concatenate([alive0, jnp.zeros(rows - n, bool)])
        state = state._replace(alive=state.alive & alive0)
    if rows > n:
        pad_dead = jnp.arange(rows) >= n
        state = state._replace(
            alive=state.alive & ~pad_dead,
            converged=state.converged | pad_dead,
        )

    if cfg.alert_quorum is not None:
        # the reference's supervisor exits at counter = nodes while the
        # factory spawned nodes+1 actors (Program.fs:169-176,53): global
        # convergence = all-but-(population - quorum) settled. Padding
        # rows are pre-settled above, so they shift the threshold.
        q = cfg.alert_quorum + (rows - n)

        def done_fn(state, _q=q):  # noqa: F811 — quorum supervisor
            settled = jnp.sum(
                (state.converged | ~state.alive).astype(jnp.int32))
            return settled >= _q

    return state, core, done_fn, extra_stats, (all_alive, targets_alive)


def default_platform() -> str:
    """The platform the default device lives on ("tpu", "cpu", ...) —
    selects compiled Mosaic kernels vs the Pallas interpreter for the
    routed delivery. ``jax_default_device`` may hold a Device or a bare
    platform string."""
    dev = jax.config.jax_default_device
    if dev is None:
        return jax.default_backend()
    return dev if isinstance(dev, str) else dev.platform


def require_invertible(topo: Topology) -> None:
    """delivery='invert' precondition: the dense table must be in use.

    ``use_dense`` can be False for three distinct reasons; name the one
    that actually applies so the error diagnoses the right knob.
    """
    import os

    from gossipprotocol_tpu.protocols.sampling import (
        DENSE_MAX_DEGREE, use_dense,
    )

    if topo.asymmetric:
        raise ValueError(
            "delivery='invert' needs a symmetric simple graph; this "
            "reference-quirks topology carries directed/self/duplicate "
            "entries — use delivery='scatter'"
        )
    if use_dense(topo):
        return
    if topo.implicit_full:
        why = ("the implicit complete graph has no neighbor table to "
               "invert (neighbors are sampled, never materialized)")
    elif os.environ.get("GOSSIP_TPU_DENSE", "1") == "0":
        why = "GOSSIP_TPU_DENSE=0 disables the dense table"
    else:
        why = (f"max degree {int(topo.degree.max())} exceeds "
               f"DENSE_MAX_DEGREE={DENSE_MAX_DEGREE} (hub graphs keep "
               "the CSR path)")
    raise ValueError(
        f"delivery='invert' needs the dense neighbor table: {why} — "
        "use delivery='scatter'"
    )


def effective_keep_alive(topo: Topology, cfg: RunConfig) -> bool:
    """The keep-alive rule actually in force (single source of truth for
    the single-chip and sharded engines plus the stall stat).

    Reference mode renders Actor2's asymmetry: the keep-alive driver is
    started for line/3D/imp3D gossip (``Program.fs:200,271``) but NOT
    for the full topology (``Program.fs:224-228`` sends no ``Adder``) —
    reference-mode full-topology gossip runs without the liveness net.
    """
    ref = cfg.semantics == "reference"
    return cfg.keep_alive and not (ref and topo.kind == "full")


def gossip_inversion_enabled(topo: Topology, cfg: RunConfig) -> bool:
    """Compile gossip with the gather-inverted delivery branch?

    On for every dense-table gossip run (``GOSSIP_TPU_INVERT=0`` opts
    out). Legality is *runtime*-checked on device each round (the branch
    is taken only while every eligible node is spreading), so no static
    condition beyond "the dense table and its inversion tables exist" is
    needed — faults, birth exclusions, and ``keep_alive=False`` simply
    keep the scatter branch selected.
    """
    import os

    from gossipprotocol_tpu.protocols.sampling import use_dense

    return (
        cfg.algorithm == "gossip"
        # reverse-slot tables pair each edge with its mirror; quirk
        # topologies (directed extras, self-loops, duplicates) have none
        and not topo.asymmetric
        and os.environ.get("GOSSIP_TPU_INVERT", "1") != "0"
        and use_dense(topo)
        # inversion reconstructs deliveries from "every spreader sent";
        # a poisson clock idles spreaders, so the branch is never legal
        and cfg.clock == "sync"
    )


def run_clock_spec(topo: Topology, cfg: RunConfig) -> tuple:
    """The static activation-clock spec for this run (single source of
    truth for both engines and the counter/predictor paths).

    ``()`` for the synchronous clock — every round core treats the empty
    tuple as "trace the literal synchronous program". Under
    ``clock='poisson'`` the spec is ``(rate, id_div)`` where ``id_div``
    groups nodes onto one shared clock: 1 normally (independent per-node
    Poisson processes), the GALA group size for ``workload='gala'`` so a
    whole learner group gossips — or idles — as one unit.
    """
    if cfg.clock == "sync":
        return ()
    from gossipprotocol_tpu.async_ import clock_spec

    id_div = 1
    if cfg.workload == "gala":
        if topo.num_nodes % cfg.groups:
            raise ValueError(
                f"workload='gala' splits {topo.num_nodes} nodes into "
                f"{cfg.groups} equal groups — nodes must be divisible "
                "by groups"
            )
        id_div = topo.num_nodes // cfg.groups
    return clock_spec(cfg.clock, cfg.activation_rate, id_div=id_div)


def note_hub_split(tel, topo) -> None:
    """Stamp the hub-splitting layout geometry on the telemetry hub —
    report/manifest surface it as ``hub split: N classes -> M
    sub-classes (max degree D)``. Computed from the degree census (the
    split is a pure function of the populated degree classes, same on
    every delivery path). Left unset — not zeroed — on degree-regular
    graphs, so pre-split manifests and records stay byte-identical."""
    from gossipprotocol_tpu.ops.delivery import degree_classes

    deg = np.asarray(topo.degree)
    cls = np.unique(degree_classes(deg))
    split = [int(c) for c in cls if 2 * c > 128]
    if split:
        tel.hub_split = {
            "classes": len(split),
            "subclasses": int(sum((2 * c) // 128 for c in split)),
            "max_degree": int(deg.max()),
        }


def device_arrays(topo: Topology, cfg: RunConfig, tel=None):
    """The runtime adjacency pytree the chunk runner threads through:
    sampled neighbor tables for the single-target senders (plus the
    reverse-slot inversion tables for dense gossip), the edge list for
    fanout-all diffusion (which draws nothing and walks every edge).

    ``tel`` (an :mod:`~gossipprotocol_tpu.obs` telemetry hub or None)
    receives the routed plan's cache provenance — whether the tables were
    loaded (``hit``), compiled (``miss``), or built uncached (``off``).

    For ``workload='sgp'`` the per-node least-squares shard rides along in
    an :class:`~gossipprotocol_tpu.learn.SGPBundle` wrapping the delivery
    pytree — same slot, so the chunk runner and ``shard_map`` specs treat
    data rows exactly like neighbor rows.
    """
    if hasattr(topo, "csr_slice"):
        # streamed builds carry per-shard CSR slices, never the global
        # adjacency this pytree is assembled from — the slices are only
        # consumable on the sharded routed designs (--devices > 1)
        raise ValueError(
            "a streamed topology build has no global adjacency for the "
            "single-chip engine — run with --devices > 1 (sharded routed "
            "push-sum) or use --build materialized")
    if cfg.algorithm == "push-sum" and cfg.workload in ("sgp", "gala"):
        from gossipprotocol_tpu.learn import SGPBundle, make_least_squares

        # groups rides along with the workload: replace both or the
        # re-run __post_init__ rejects groups>1 without workload='gala'
        inner_cfg = dataclasses.replace(cfg, workload="avg", groups=1)
        inner = device_arrays(topo, inner_cfg, tel)
        a, b, _ = make_least_squares(
            topo.num_nodes, cfg.payload_dim, cfg.sgp_samples, cfg.seed,
            dtype=np.dtype(jnp.dtype(cfg.dtype).name),
        )
        return SGPBundle(nbrs=inner, A=jnp.asarray(a), b=jnp.asarray(b))
    if cfg.algorithm == "push-sum" and cfg.fanout == "all":
        if cfg.delivery == "routed":
            from gossipprotocol_tpu.ops.delivery import (
                routed_streamed_bytes_per_round,
            )
            from gossipprotocol_tpu.ops.plancache import routed_delivery_cached

            rd, prov = routed_delivery_cached(topo, cache_dir=cfg.plan_cache)
            if tel is not None and tel.enabled:
                tel.event(
                    "plan_cache", provenance=prov, design="single-chip",
                    streamed_bytes_per_round=routed_streamed_bytes_per_round(
                        rd),
                )
            if tel is not None:
                note_hub_split(tel, topo)
            return rd
        if cfg.delivery in ("pallas", "megakernel"):
            from gossipprotocol_tpu.ops.pallasdelivery import (
                pallas_streamed_bytes_per_round,
            )
            from gossipprotocol_tpu.ops.plancache import pallas_delivery_cached

            pd, prov = pallas_delivery_cached(topo, cache_dir=cfg.plan_cache)
            if tel is not None and tel.enabled:
                tel.event(
                    "plan_cache", provenance=prov, design="single-chip",
                    delivery=cfg.delivery,
                    streamed_bytes_per_round=pallas_streamed_bytes_per_round(
                        pd),
                )
            if tel is not None:
                note_hub_split(tel, topo)
            if use_megakernel(cfg):
                # same cached gather plans, wrapped with the precomputed
                # f32 degree; eligibility (resident gathers) is checked
                # loudly here, before any compile
                from gossipprotocol_tpu.ops.megakernel import (
                    build_megakernel_delivery,
                )

                return build_megakernel_delivery(pd)
            return pd
        from gossipprotocol_tpu.protocols.diffusion import diffusion_edges

        return diffusion_edges(topo)
    if cfg.algorithm == "push-sum" and cfg.delivery == "invert":
        from gossipprotocol_tpu.protocols.gossip import inverted_dense

        require_invertible(topo)  # same gate for direct callers
        return inverted_dense(topo)
    if gossip_inversion_enabled(topo, cfg):
        from gossipprotocol_tpu.protocols.gossip import inverted_dense

        return inverted_dense(topo)
    return device_topology(topo)


def gossip_spreading_count(state: GossipState, keep_alive: bool) -> jax.Array:
    """Nodes still able to deliver a hit. Zero while unconverged means the
    rumor is dead (e.g. the seed node was fault-killed, or keep_alive=False
    let every spreader go silent — the reference's liveness hole) and the
    run can never progress: the driver stalls out instead of grinding to
    max_rounds."""
    heard = (state.counts >= 1) & state.alive
    if not keep_alive:
        heard = heard & ~state.converged
    return jnp.sum(heard.astype(jnp.int32))


def chunk_stats(state, done_fn) -> dict:
    """On-device summary scalars for one chunk (SURVEY.md §5.5 metrics).

    Computed inside the jitted chunk call and fetched in a *single* host
    transfer — on a tunneled TPU each separate ``int(...)`` costs a
    round-trip, which would otherwise dominate small runs' wall-clock.
    Phantom/dead rows are excluded by construction (``alive`` is False
    there).
    """
    rec = {
        "round": state.round,
        "done": done_fn(state),
        "converged": jnp.sum((state.converged & state.alive).astype(jnp.int32)),
        "alive": jnp.sum(state.alive.astype(jnp.int32)),
    }
    if hasattr(state, "ratio"):  # PushSumState and the reference WalkState
        big = jnp.asarray(jnp.inf, state.ratio.dtype)
        # vector payloads: broadcast the per-node mask over the d columns
        live = (state.alive if state.ratio.ndim == 1
                else state.alive[:, None])
        rec["ratio_min"] = jnp.min(jnp.where(live, state.ratio, big))
        rec["ratio_max"] = jnp.max(jnp.where(live, state.ratio, -big))
        # dry-spell underflow detector (the measured 100M f32 wall): an
        # alive node with w == 0 has halved through the float subnormals
        # during a receipt dry spell — its ratio is garbage and the
        # global predicate can never certify it. Counted on device so
        # the driver can warn with the cure instead of grinding silently.
        rec["w_underflow"] = jnp.sum(
            (state.alive & (state.w == 0)).astype(jnp.int32)
        )
    return rec


def stats_with_extra(state, done_fn, extra_stats) -> dict:
    rec = chunk_stats(state, done_fn)
    if extra_stats is not None:
        rec.update(extra_stats(state))
    return rec


def mass_stats(state, all_sum=sum0) -> dict:
    """On-device conservation scalars for the telemetry counters: total
    push-sum mass ``(Σs, Σw)`` over every row, in the state dtype. The
    walk's in-flight token carries real mass, so it is included. Empty
    for mass-free states (gossip). ``all_sum`` is the cross-shard
    reduction under ``shard_map``.

    The drift baseline is taken from the *same compiled reduction* (a
    no-op ``step(state, -1)`` at drive start), so a lossless run reports
    exactly 0 ULPs — comparing against an eager host sum would
    manufacture drift out of reduction-order rounding.

    Vector payloads report per-dimension mass (``mass_s`` is a [d]
    vector); the drift tracker takes the max over dimensions. SGP states
    are excluded entirely — the gradient step injects mass by design, so
    "drift" would only measure the optimizer."""
    if not hasattr(state, "s") or hasattr(state, "loss"):
        return {}
    ms = all_sum(state.s)
    mw = all_sum(state.w)
    if hasattr(state, "msg_s"):
        ms = ms + state.msg_s
        mw = mw + state.msg_w
    return {"mass_s": ms, "mass_w": mw}


def make_chunk_runner(round_core, done_fn, extra_stats=None,
                      counter_fn=None, counter_slots=0,
                      trace_fn=None, trace_slots=0, *,
                      rounds_per_step=1, sentinel_fn=None):
    """jitted ``(state, nbrs, base_key, round_limit) -> (state, stats)``:
    advance rounds until global convergence or ``state.round ==
    round_limit``. The supervisor predicate is evaluated in the loop
    condition — the reference's flow 3.4 folded into cond_fun — and again
    in the returned stats so the host loop needs one fetch per chunk.

    ``rounds_per_step`` is the megakernel super-step width K: one body
    call advances up to K rounds, so the counter/trace buffers carry
    ``K - 1`` spare rows (a super-step entered at ``round_limit - 1``
    can overshoot the chunk by that much) and each body call stamps K
    buffer rows. The per-round counter delta is constant on the
    megakernel's all-alive synchronous path (``sent = delivered =
    Σ degree``), so broadcasting one delta row is exact; the trace row
    repeats the super-step's final state — per-round residual detail
    degrades to K-round granularity, the documented trade. The host's
    valid-prefix slicing (``[: cur_round - chunk_start]``) drops the
    rows a frozen-on-convergence super-step never reached. At the
    default K=1 every expression below reduces to the literal
    pre-megakernel program (the one the goldens pin).

    ``counter_fn`` (obs/counters.py contract) folds an int32
    ``[counter_slots, 3]`` message-count buffer through the scan — one
    delta row per round, read back with the chunk stats. With it unset
    the traced program is *identical* to before telemetry existed (the
    zero-cost-off contract); with it set the state trajectory is still
    bitwise unchanged because the buffer never feeds back into the round.

    ``trace_fn`` (obs/trace.py contract) additionally folds a float32
    ``[trace_slots, NUM_TRACE_COLS]`` per-round convergence-trace buffer
    through the scan under the same contract: unset keeps the literal
    counter-only (or pre-telemetry) program; set never feeds back into
    the round, so the state trajectory stays bitwise identical.

    ``sentinel_fn`` (the health sentinel, ``make_sentinel_fn``) joins the
    loop *condition* only: the chunk exits at the first round whose state
    trips it (the trip condition persists in the state — NaN stays NaN —
    so the post-loop stats re-detect it), leaving every body, carry and
    buffer untouched. Unset, ``stop_fn is done_fn`` and the traced
    program is the literal pre-sentinel one (the goldens' byte-identical
    zero-cost-off contract); set, it adds a ``sentinel_trip`` stat plus
    the mass totals the host tripwire compares.
    """
    stop_fn = (done_fn if sentinel_fn is None
               else lambda s: jnp.logical_or(done_fn(s), sentinel_fn(s)))

    def sentinel_stats(final, stats):
        if sentinel_fn is not None:
            stats["sentinel_trip"] = sentinel_fn(final).astype(jnp.int32)
            if "mass_s" not in stats:
                stats.update(mass_stats(final))
        return stats

    if counter_fn is None and trace_fn is None:
        def chunk(state, nbrs, base_key, round_limit):
            def body(s):
                return round_core(s, nbrs, base_key)

            def cond(s):
                return jnp.logical_and(~stop_fn(s), s.round < round_limit)

            final = jax.lax.while_loop(cond, body, state)
            return final, sentinel_stats(
                final, stats_with_extra(final, done_fn, extra_stats))

        return jax.jit(chunk, donate_argnums=0)

    k = rounds_per_step

    def counter_rows(delta):
        return (delta[None, :] if k == 1
                else jnp.broadcast_to(delta[None, :], (k, 3)))

    if trace_fn is None:
        def chunk(state, nbrs, base_key, round_limit):
            start = state.round  # chunk entry round: buffer row 0

            def body(carry):
                s, buf = carry
                s2 = round_core(s, nbrs, base_key)
                delta = counter_fn(s, s2, nbrs, base_key, s.alive, None)
                buf = jax.lax.dynamic_update_slice(
                    buf, counter_rows(delta),
                    (s.round - start, jnp.int32(0)))
                return s2, buf

            def cond(carry):
                s, _ = carry
                return jnp.logical_and(~stop_fn(s), s.round < round_limit)

            buf0 = jnp.zeros((counter_slots + k - 1, 3), jnp.int32)
            final, buf = jax.lax.while_loop(cond, body, (state, buf0))
            stats = stats_with_extra(final, done_fn, extra_stats)
            stats["counters"] = buf
            stats.update(mass_stats(final))
            return final, sentinel_stats(final, stats)

        return jax.jit(chunk, donate_argnums=0)

    from gossipprotocol_tpu.obs.trace import NUM_TRACE_COLS

    def trace_rows(row_vec):
        return (row_vec[None, :] if k == 1
                else jnp.broadcast_to(row_vec[None, :],
                                      (k, NUM_TRACE_COLS)))

    def chunk(state, nbrs, base_key, round_limit):
        start = state.round  # chunk entry round: buffer row 0

        def body(carry):
            s, bufs = carry
            s2 = round_core(s, nbrs, base_key)
            row = s.round - start
            bufs = dict(bufs)
            if counter_fn is not None:
                delta = counter_fn(s, s2, nbrs, base_key, s.alive, None)
                bufs["counters"] = jax.lax.dynamic_update_slice(
                    bufs["counters"], counter_rows(delta),
                    (row, jnp.int32(0)))
            bufs["trace"] = jax.lax.dynamic_update_slice(
                bufs["trace"],
                trace_rows(trace_fn(s2).astype(jnp.float32)),
                (row, jnp.int32(0)))
            return s2, bufs

        def cond(carry):
            s, _ = carry
            return jnp.logical_and(~stop_fn(s), s.round < round_limit)

        bufs0 = {
            "trace": jnp.zeros((trace_slots + k - 1, NUM_TRACE_COLS),
                               jnp.float32),
        }
        if counter_fn is not None:
            bufs0["counters"] = jnp.zeros((counter_slots + k - 1, 3),
                                          jnp.int32)
        final, bufs = jax.lax.while_loop(cond, body, (state, bufs0))
        stats = stats_with_extra(final, done_fn, extra_stats)
        stats["trace"] = bufs["trace"]
        if counter_fn is not None:
            stats["counters"] = bufs["counters"]
            stats.update(mass_stats(final))
        return final, sentinel_stats(final, stats)

    return jax.jit(chunk, donate_argnums=0)


def revive_rows(state, ids, cfg: RunConfig, num_nodes: int):
    """Reset rows ``ids`` to fresh-born state — a crashed process
    restarting from its initial value, not a resurrected one.

    Runs on device via ``.at[ids].set`` between chunks — never through a
    host round-trip. A numpy buffer zero-copy ``device_put`` into a field
    that the next chunk *donates* lets XLA alias externally-owned memory,
    and the eventual host fetch can then read one field's bytes through
    another field's view (observed on CPU as ``w == s``). Gossip rows
    drop to zero hearings; push-sum rows get their init ``(s, w)`` back —
    the values are precomputed in numpy in the state dtype exactly as
    :func:`~gossipprotocol_tpu.protocols.state.pushsum_init` computes
    them (same IEEE division), so a revived trajectory is bitwise
    identical single-chip vs sharded. The node's stranded pre-death mass
    is discarded with the overwrite (it was already excluded from every
    healthy-mean computation while dead). Callers flip ``alive``
    separately — this touches only protocol state.
    """
    import jax.numpy as jnp

    ids = np.asarray(ids, dtype=np.int64)
    idx = jnp.asarray(ids, dtype=jnp.int32)

    def put(field, values):
        out = field.at[idx].set(values)
        if out.sharding != field.sharding:  # compiled step expects layout
            out = jax.device_put(out, field.sharding)
        return out

    if hasattr(state, "counts"):  # GossipState
        return state._replace(
            counts=put(state.counts, 0),
            converged=put(state.converged, False),
        )
    dt = np.dtype(state.s.dtype)
    if state.s.ndim == 2:
        if hasattr(state, "loss"):
            # SGP: fresh-born nodes restart at the shared x₀ = 0 — the
            # crashed-process analogue of the scalar init-value reset
            vals_np = np.zeros((ids.size, state.s.shape[1]), dt)
        else:
            from gossipprotocol_tpu.protocols.state import (
                pushsum_payload_values,
            )

            # same IEEE arithmetic as the device init: int index → dtype
            # cast → divide by dtype(n), so revived rows are bitwise the
            # init rows
            vals_np = pushsum_payload_values(
                ids, num_nodes, state.s.shape[1], cfg.value_mode, dt, np)
    else:
        vals_np = (ids.astype(dt) / dt.type(num_nodes)
                   if cfg.value_mode == "scaled" else ids.astype(dt))
    vals = jnp.asarray(vals_np)
    streak0 = 1 if cfg.semantics == "reference" else 0
    return state._replace(
        s=put(state.s, vals),
        w=put(state.w, 1),
        ratio=put(state.ratio, vals),  # w == 1, so ratio == s exactly
        streak=put(state.streak, streak0),
        converged=put(state.converged, False),
    )


# Host mass-drift tripwire threshold (ULPs of the anchored baseline).
# Far above the worst honest drift the observatory ever flagged
# (DRIFT_ULP_TOL = 64 is the *anomaly* bar; exact-conservation runs sit
# at 0), far below any adversarial scale:K injection's displacement.
SENTINEL_MASS_ULPS = 256.0


def sentinel_bad_mask(state):
    """Per-row health predicate of the on-device sentinel: an *alive* row
    is bad when its payload ``s`` has a non-finite component, or ``w`` is
    non-finite or negative. ``w == 0`` is deliberately healthy — the
    documented receipt-dry-spell underflow (``w_underflow`` warns), not a
    data fault. Shared by the device trip check (any over local rows) and
    the host's offending-row identification, so they cannot disagree."""
    xp = jnp if isinstance(state.s, jax.Array) else np
    s_bad = ~xp.isfinite(state.s)
    if state.s.ndim == 2:
        s_bad = s_bad.any(axis=1)
    return state.alive & (s_bad | ~xp.isfinite(state.w) | (state.w < 0))


def make_sentinel_fn(cfg: RunConfig):
    """The single-chip sentinel trip predicate (``state -> bool``) for
    :func:`make_chunk_runner`'s loop condition. The sharded engine wraps
    :func:`sentinel_bad_mask` in its own psum reduction instead."""
    del cfg  # the predicate is config-independent once sentinel is on

    def sentinel_fn(state):
        return jnp.any(sentinel_bad_mask(state))

    return sentinel_fn


def quarantine_rows(state, ids):
    """Zero the protocol mass of rows ``ids`` on device — the first step
    of a quarantine, BEFORE the synthetic kill fires through the event
    pipeline: the poison (NaN/Inf/adversarial mass) must leave the sums
    the instant the nodes leave the network, or every later conservation
    snapshot and mass stat stays NaN forever. Same ``.at[].set`` +
    sharding-restore discipline as :func:`revive_rows` (a zero-copy
    device_put into a donated buffer would alias externally-owned
    memory). Callers flip ``alive`` separately (the pipeline does)."""
    idx = jnp.asarray(np.asarray(ids, np.int64), jnp.int32)

    def put(field, values):
        out = field.at[idx].set(values)
        if out.sharding != field.sharding:
            out = jax.device_put(out, field.sharding)
        return out

    out = state._replace(s=put(state.s, 0), w=put(state.w, 0))
    if hasattr(state, "ratio"):
        out = out._replace(ratio=put(state.ratio, 0))
    return out


def inject_value_fault(state, ids, spec, cfg: RunConfig, num_nodes: int):
    """Apply one value-fault event to rows ``ids`` (already filtered to
    live nodes): corrupt the push-sum numerator ``s`` per the spec's
    model. ``w`` and the rest of the state are untouched — the fault
    models a node whose *value* went wrong, not its protocol machinery.
    Device-side ``.at[].set``/``.multiply``, same aliasing discipline as
    :func:`revive_rows`."""
    ids = np.asarray(ids, np.int64)
    idx = jnp.asarray(ids, jnp.int32)
    dt = np.dtype(state.s.dtype)

    def put(out):
        if out.sharding != state.s.sharding:  # compiled step expects layout
            out = jax.device_put(out, state.s.sharding)
        return out

    model = str(spec.model).split(":", 1)[0]
    if model == "nan":
        return state._replace(s=put(state.s.at[idx].set(dt.type(np.nan))))
    if model == "inf":
        return state._replace(s=put(state.s.at[idx].set(dt.type(np.inf))))
    if model == "scale":
        k = dt.type(spec.scale_factor())
        return state._replace(s=put(state.s.at[idx].multiply(k)))
    # model == "stuck": payload resets to the node's initial value — a
    # learner that stopped learning but keeps gossiping its stale state
    if state.s.ndim == 2 and hasattr(state, "loss"):
        vals_np = np.zeros((ids.size, state.s.shape[1]), dt)  # SGP x₀ = 0
    elif state.s.ndim == 2:
        from gossipprotocol_tpu.protocols.state import pushsum_payload_values

        vals_np = pushsum_payload_values(
            ids, num_nodes, state.s.shape[1], cfg.value_mode, dt, np)
    else:
        vals_np = (ids.astype(dt) / dt.type(num_nodes)
                   if cfg.value_mode == "scaled" else ids.astype(dt))
    return state._replace(s=put(state.s.at[idx].set(jnp.asarray(vals_np))))


def compute_prediction(run_topo, cfg: RunConfig, tel) -> Optional[dict]:
    """Analytic round prediction for this run (obs/predict.py), computed
    once before compiling — on the host, from the topology CSR.

    Returns None when prediction is off (no telemetry, no budget) or the
    topology is too large / the configuration unpredictable; raises when
    ``round_budget == "auto"`` cannot be resolved, since silently running
    unbudgeted is exactly what the flag exists to prevent.
    """
    if not (tel.enabled or cfg.round_budget is not None):
        return None
    from gossipprotocol_tpu.obs.predict import maybe_predict_rounds

    with tel.span("predict_rounds"):
        pred = maybe_predict_rounds(
            run_topo, cfg, required=(cfg.round_budget == "auto"))
    if cfg.round_budget == "auto" and pred is None:
        raise ValueError(
            "round_budget='auto' needs an analytic round prediction, which "
            "is unavailable for this configuration/topology (obs/predict.py "
            "gates on edge count via $GOSSIP_TPU_PREDICT_EDGE_CAP); pass an "
            "explicit --round-budget N instead"
        )
    if pred is not None and tel.enabled:
        tel.prediction = pred
        tel.event("prediction", **pred)
    return pred


# Graceful-stop hook (serve/worker SIGTERM drain): a callable checked at
# every chunk boundary of the host loop. Truthy -> save a checkpoint (when
# the run checkpoints at all) and return early with RunResult.stopped =
# "drain" instead of grinding on. Module-level rather than a RunConfig
# field so a signal handler installed before cli.main() can reach a run
# whose config it never sees.
_stop_check: Optional[Callable[[], bool]] = None


def install_stop_check(fn: Optional[Callable[[], bool]]) -> None:
    """Install (or clear, with None) the global graceful-stop check."""
    global _stop_check
    _stop_check = fn


def _mass_snapshot(state):
    """(Σs, Σw) over every row as float64 host sums — the invariant a
    repair rebuild must preserve bitwise. None for mass-free states
    (gossip counts hits, it has no conserved quantity)."""
    if not hasattr(state, "s"):
        return None
    from gossipprotocol_tpu.utils import checkpoint as ckpt_mod

    host = ckpt_mod.fetch_host((state.s, state.w))
    return (float(np.asarray(host[0], np.float64).sum()),
            float(np.asarray(host[1], np.float64).sum()))


def _drive(
    topo: Topology,
    cfg: RunConfig,
    state,
    step: Callable[[Any, int], Any],
    done_fn,
    compile_ms: float,
    trim: Callable[[Any], Any] = lambda s: s,
    rebuild: Optional[Callable] = None,
    run_topo: Optional[Topology] = None,
    prediction: Optional[dict] = None,
    reload_fn: Optional[Callable] = None,
) -> RunResult:
    """Shared host loop for the single-chip and sharded engines.

    ``step(state, round_limit) -> (state, stats)`` advances the state on
    device and returns on-device summary scalars (one host fetch per
    chunk); ``trim`` drops padding rows before anything user-visible
    (checkpoints, the returned final state).

    ``rebuild(new_topo, state) -> (step, state, info)`` re-derives the
    engine's device adjacency and compiled step for a repaired topology
    (``cfg.repair != "off"``); ``info`` is a json-able dict merged into
    the repair metrics record (plan-patch provenance). ``run_topo`` is
    the adjacency actually in force at entry — the birth topology unless
    a resume already replayed repair events past it.

    ``prediction`` is the analytic round prediction (obs/predict.py)
    computed by the engine before compiling; it resolves
    ``cfg.round_budget == "auto"`` and is updated in place with the
    actual outcome so the manifest records predicted-vs-actual.

    ``reload_fn(host_state) -> device state`` re-materializes a loaded
    checkpoint state onto the engine's device layout (the sharded engine
    pads and re-shards; default is a plain device copy). Only exercised
    by ``cfg.sentinel == "rollback"``.
    """
    from gossipprotocol_tpu.events import HostEvents
    from gossipprotocol_tpu.obs import as_telemetry
    from gossipprotocol_tpu.obs.counters import ulp_drift
    from gossipprotocol_tpu.utils import checkpoint as ckpt_mod

    tel = as_telemetry(cfg.telemetry)
    run_topo = run_topo if run_topo is not None else topo
    chunk_rounds = cfg.resolve_chunk_rounds(
        topo.num_nodes,
        None if topo.implicit_full else int(topo.num_directed_edges),
    )
    metrics: List[dict] = []
    checkpoints: List[str] = []
    chunk_i = 0
    underflow_warned = False
    # a checkpoint taken at round C reflects every event with r < C
    # (events fire at loop top for r <= cur_round; chunks stop exactly at
    # event rounds; checkpoints are written post-chunk) but never r == C.
    # On resume, HostEvents prunes exactly the strictly-past events:
    # re-firing a kill could re-kill a node revived since, and a revive
    # reset is not idempotent (it would wipe mass the node has mixed in
    # since rejoining)
    cur_round = int(np.asarray(jax.device_get(state.round)))
    host_events = HostEvents(topo, cfg, start_round=cur_round, tel=tel)
    done = False
    # round budget: an explicit int, or the analytic prediction's bound
    # ("auto" — run_simulation guarantees `prediction` is present then)
    budget = None
    if cfg.round_budget == "auto":
        budget = int(prediction["budget_rounds"])
    elif cfg.round_budget is not None:
        budget = int(cfg.round_budget)
    over_budget = False
    drained = False
    checkpointing = bool(cfg.checkpoint_every and cfg.checkpoint_dir)
    # once per run, not per checkpoint (crc over the CSR)
    adjacency = ckpt_mod.topology_fingerprint(topo) if checkpointing else None

    sentinel_on = cfg.sentinel != "off"
    # quarantines this trajectory lived through: the resumed prefix from
    # the checkpoint metadata plus everything this process performs.
    # Saved into every checkpoint (save extra_meta) so a later resume can
    # replay these dynamic kills into the adjacency like scheduled ones.
    quar_log = {int(r): np.asarray(ids, np.int64)
                for r, ids in (cfg.quarantine_log or ())}

    def quar_meta():
        if not quar_log:
            return None
        return {"quarantines": [[r, quar_log[r].tolist()]
                                for r in sorted(quar_log)]}

    mass_base = None
    if tel.counters_on or sentinel_on:
        # anchor the conservation baseline with the *same compiled
        # reduction* the chunk stats use: a no-op chunk (round_limit=-1,
        # the warm-start trick — the body never runs) returns the mass
        # sums without advancing the state. An eager host sum here would
        # manufacture ULP drift out of reduction-order rounding.
        with tel.span("mass_baseline"):
            state, _bs = step(state, -1)
            _bh = jax.device_get(_bs)
        if "mass_s" in _bh:
            mass_base = (_bh["mass_s"], _bh["mass_w"])

    t0 = time.perf_counter()
    while True:
        if cur_round >= cfg.max_rounds:
            break
        # host events (SURVEY.md §5.3 + events/): strike everything due —
        # several rounds' worth after a resume lands mid-schedule — in
        # round order through the unified pipeline (kills, revives, edge
        # churn, repair, one partition pass); the round_limit below
        # guarantees we stop exactly at the next scheduled event so none
        # can be skipped
        if host_events.due(cur_round):
            state, run_topo, new_step, event_recs, reborn_count = \
                host_events.fire(state, run_topo, cur_round, rebuild)
            if new_step is not None:
                step = new_step
            for rec in event_recs:
                metrics.append(rec)
                tel.metric(rec)
                if cfg.metrics_callback:
                    cfg.metrics_callback(rec)
            if reborn_count and mass_base is not None:
                # revive_rows overwrote rows with fresh-born (s, w):
                # the conserved quantity itself legitimately changed
                # (stranded pre-death mass discarded) — re-anchor the
                # drift baseline with the same no-op-chunk reduction
                state, _bs = step(state, -1)
                _bh = jax.device_get(_bs)
                mass_base = (_bh["mass_s"], _bh["mass_w"])

        next_event = host_events.next_round(cfg.max_rounds)
        round_limit = min(cur_round + chunk_rounds, cfg.max_rounds, next_event)
        if budget is not None:
            # stop exactly at the budget so the over-budget record carries
            # the budget round, not the chunk boundary past it
            round_limit = min(round_limit, budget)

        chunk_start = cur_round
        with tel.span("chunk", round_start=cur_round,
                      round_limit=round_limit):
            state, stats = step(state, round_limit)
            chunk_i += 1
            # the device_get is the sync point, so the span covers the
            # on-device work, not just the dispatch
            host = jax.device_get(stats)  # the one blocking transfer per chunk
        cur_round = int(host.pop("round"))
        done = bool(host.pop("done"))
        trip_dev = bool(host.pop("sentinel_trip", 0))
        counters = host.pop("counters", None)
        shard_counters = host.pop("shard_counters", None)
        trace_buf = host.pop("trace", None)
        chunk_mass = (host.pop("mass_s", None), host.pop("mass_w", None))
        if trace_buf is not None and cur_round > chunk_start:
            # valid prefix only: one row per round this chunk executed
            tel.add_trace_rows(
                chunk_start,
                np.asarray(trace_buf)[: cur_round - chunk_start])
        rec = {"round": cur_round, **{k: v.item() for k, v in host.items()}}
        if counters is not None:
            # per-round int32 delta rows; cumulative totals as Python
            # ints so multi-billion-message runs never overflow
            sent, delivered, dropped = (
                int(x) for x in np.asarray(counters, np.int64).sum(axis=0))
            rec["sent"] = sent
            rec["delivered"] = delivered
            rec["dropped"] = dropped
            tel.add_counters(sent, delivered, dropped)
            if shard_counters is not None:
                # per-shard attribution: the unreduced partials, gathered
                # as [num_shards * slots, 3]. Their sum over shards must
                # reproduce the psum'd totals *bitwise* — int32 addition
                # is exact, so any mismatch means the attribution buffer
                # diverged from the reduced one
                sc = np.asarray(shard_counters, np.int64)
                per_shard = sc.reshape(-1, counters.shape[0], 3).sum(axis=1)
                total = per_shard.sum(axis=0)
                if (total != np.asarray([sent, delivered, dropped])).any():
                    raise AssertionError(
                        f"per-shard counter partials do not sum to the "
                        f"reduced totals: {per_shard.tolist()} -> "
                        f"{total.tolist()} != "
                        f"{[sent, delivered, dropped]} (round={cur_round})"
                    )
                tel.add_shard_counters(per_shard)
        mass_trip = False
        if chunk_mass[0] is not None and mass_base is not None:
            s_ulps = ulp_drift(chunk_mass[0], mass_base[0])
            w_ulps = ulp_drift(chunk_mass[1], mass_base[1])
            rec["mass_drift_ulps"] = s_ulps
            rec["w_drift_ulps"] = w_ulps
            tel.note_mass_drift(s_ulps, w_ulps)
            if sentinel_on:
                # host mass-drift tripwire: conservation displaced far
                # beyond honest rounding (or into NaN/Inf, where the ULP
                # measure itself degenerates)
                mass_trip = any(
                    (not np.isfinite(u)) or u > SENTINEL_MASS_ULPS
                    for u in (s_ulps, w_ulps))
        if rec.get("w_underflow", 0) and not underflow_warned:
            # measured failure mode (README "Convergence-predicate
            # soundness", 100M artifact): warn once with the cures
            # instead of grinding to max_rounds with garbage ratios
            import sys as _sys

            print(
                f"warning: {rec['w_underflow']} alive node(s) underflowed "
                "w to 0 in a receipt dry spell — float32 single-target "
                "push-sum cannot certify convergence past this point. "
                "Use --fanout all (no dry spells by construction) or "
                "--x64 (covers ~1000-round gaps).",
                file=_sys.stderr,
            )
            underflow_warned = True
        stalled = not done and rec.get("spreading") == 0
        if stalled:
            # gossip liveness failure: no node can ever deliver another hit
            # (seed fault-killed, or keep_alive=False silenced everyone —
            # the reference's Actor2 hole); grinding to max_rounds is
            # pointless
            rec["stalled"] = True
        trip = trip_dev or mass_trip
        if trip:
            rec["sentinel_trip"] = True
        metrics.append(rec)
        tel.metric(rec)
        if cfg.metrics_callback:
            cfg.metrics_callback(rec)
        if trip:
            # sentinel trip handling, BEFORE the checkpoint save: under
            # quarantine/rollback no poisoned state is ever published, so
            # every checkpoint on disk predates its trip by construction
            # (what makes "newest checkpoint predating the trip" sound).
            # Offender identification is host-side from the fetched state
            # — bitwise invariant across shard counts, so the quarantined
            # set (and everything downstream) is too.
            bad_host = ckpt_mod.fetch_host(trim(state))
            bad_ids = np.flatnonzero(np.asarray(sentinel_bad_mask(bad_host)))
            ev = {
                "event": "sentinel_trip",
                "round": cur_round,
                "cause": "nonfinite" if trip_dev else "mass_drift",
                "nodes": int(bad_ids.size),
                "mode": cfg.sentinel,
            }
            metrics.append(ev)
            tel.metric(ev)
            tel.event("sentinel_trip",
                      **{k: v for k, v in ev.items() if k != "event"})
            if cfg.metrics_callback:
                cfg.metrics_callback(ev)

            def requarantine(at_round, ids):
                nonlocal state, run_topo, step
                state, run_topo, new_step, q_recs = host_events.quarantine(
                    state, run_topo, at_round, ids, rebuild)
                if new_step is not None:
                    step = new_step
                for qr in q_recs:
                    metrics.append(qr)
                    tel.metric(qr)
                    if cfg.metrics_callback:
                        cfg.metrics_callback(qr)
                    if qr.get("event") == "quarantine":
                        tel.event("quarantine", round=at_round,
                                  nodes=qr["nodes"], policy=qr["policy"])
                quar_log[at_round] = np.union1d(
                    quar_log.get(at_round, np.empty(0, np.int64)), ids)

            def reanchor():
                nonlocal state, mass_base
                if mass_base is None:
                    return
                state, _bs = step(state, -1)
                _bh = jax.device_get(_bs)
                mass_base = (_bh["mass_s"], _bh["mass_w"])

            if cfg.sentinel in ("quarantine", "rollback") and bad_ids.size:
                if rebuild is None:
                    raise RuntimeError(
                        "sentinel tripped but the engine supplied no "
                        "rebuild hook for quarantine")
                target = None
                if cfg.sentinel == "rollback":
                    # newest readable checkpoint strictly predating the
                    # trip (all published ones are clean, see above)
                    for path in ckpt_mod.candidates(cfg.checkpoint_dir):
                        try:
                            c_meta = ckpt_mod.peek_meta(path)
                        except Exception:
                            continue
                        if int(c_meta.get("round", cur_round)) < cur_round:
                            target = (path, int(c_meta["round"]))
                            break
                if target is not None:
                    c_path, c_round = target
                    with tel.span("sentinel_rollback", round=cur_round,
                                  target_round=c_round):
                        rb_state, _rb_meta = ckpt_mod.load(c_path)
                        # quarantines from the now-abandoned timeline
                        # (r > C) are dropped; one at exactly C merges
                        # with the new bad set — the restored state
                        # predates it, so it must be re-applied whole
                        merged = np.union1d(
                            quar_log.get(c_round, np.empty(0, np.int64)),
                            bad_ids)
                        quar_log = {r: v for r, v in quar_log.items()
                                    if r < c_round}
                        from gossipprotocol_tpu.events import (
                            replay_topology_events,
                        )

                        run_topo = replay_topology_events(
                            topo, cfg.schedule, cfg.events, cfg.repair,
                            cfg.seed, c_round, quarantines=quar_log)
                        state = (reload_fn if reload_fn is not None
                                 else lambda st: jax.tree.map(jnp.array, st)
                                 )(rb_state)
                        # fresh engine at C restores the events the old
                        # instance already consumed on the abandoned path
                        host_events = HostEvents(topo, cfg,
                                                 start_round=c_round,
                                                 tel=tel)
                        prev_topo = run_topo
                        requarantine(c_round, merged)
                        if run_topo is prev_topo and (
                                cfg.repair != "off"
                                or cfg.events.has_events):
                            # the quarantine itself changed nothing, but
                            # the adjacency at C can still differ from
                            # the one the current compiled step was built
                            # against (the abandoned timeline evolved it)
                            step, state, _ = rebuild(run_topo, state)
                        cur_round = c_round
                        rb_rec = {"event": "rollback", "round": cur_round,
                                  "from_round": int(ev["round"]),
                                  "checkpoint": c_path,
                                  "nodes": int(merged.size)}
                        metrics.append(rb_rec)
                        tel.metric(rb_rec)
                        tel.event("rollback", round=cur_round,
                                  from_round=int(ev["round"]),
                                  nodes=int(merged.size))
                        if cfg.metrics_callback:
                            cfg.metrics_callback(rb_rec)
                else:
                    if cfg.sentinel == "rollback":
                        # no checkpoint predates the trip (it fired before
                        # the first save) — contain in place instead
                        fb = {"event": "rollback_fallback",
                              "round": cur_round,
                              "reason": "no checkpoint predates the trip"}
                        metrics.append(fb)
                        tel.metric(fb)
                        if cfg.metrics_callback:
                            cfg.metrics_callback(fb)
                    requarantine(cur_round, bad_ids)
                # quarantine zeroed rows: the conserved quantity itself
                # legitimately changed — re-anchor the drift baseline
                reanchor()
                continue
            if trip_dev:
                # detect-only mode cannot remove the poison, and a
                # tripped state re-trips the loop condition forever:
                # record and stop (the run could never converge anyway)
                break
            # unattributable mass-level trip (e.g. a finite scale:K
            # displacement) with no containable rows: accept the new mass
            # level so one shift does not re-trip every following chunk
            reanchor()
        if checkpointing and chunk_i % cfg.checkpoint_every == 0:
            with tel.span("checkpoint_save", round=cur_round):
                checkpoints.append(
                    ckpt_mod.save(
                        cfg.checkpoint_dir, trim(state), cfg, topo.kind,
                        adjacency=adjacency, extra_meta=quar_meta(),
                    )
                )
        if budget is not None and not done and cur_round >= budget:
            # structured over-budget exit: the run is not converging at
            # the configured (or predicted) rate — stop burning rounds
            # and leave an analyzable record instead of grinding on to
            # max_rounds
            over_budget = True
            ob = {
                "event": "over_budget",
                "round": cur_round,
                "budget_rounds": budget,
                "budget_source": ("auto" if cfg.round_budget == "auto"
                                  else "explicit"),
            }
            if prediction is not None:
                ob["predicted_rounds"] = prediction.get("predicted_rounds")
            metrics.append(ob)
            tel.metric(ob)
            tel.event("over_budget", **{k: v for k, v in ob.items()
                                        if k != "event"})
            if cfg.metrics_callback:
                cfg.metrics_callback(ob)
        if not done and _stop_check is not None and _stop_check():
            # graceful drain (serve/worker SIGTERM): save a checkpoint
            # off-cadence so the resume loses nothing, leave a structured
            # record, and hand back a result stamped "drain" — the run is
            # paused, not finished
            drained = True
            if checkpointing:
                with tel.span("checkpoint_save", round=cur_round,
                              reason="drain"):
                    checkpoints.append(
                        ckpt_mod.save(
                            cfg.checkpoint_dir, trim(state), cfg, topo.kind,
                            adjacency=adjacency, extra_meta=quar_meta(),
                        )
                    )
            rec = {"event": "drained", "round": cur_round,
                   "checkpointed": checkpointing}
            metrics.append(rec)
            tel.metric(rec)
            tel.event("drained", round=cur_round, checkpointed=checkpointing)
            if cfg.metrics_callback:
                cfg.metrics_callback(rec)
        if done or stalled or over_budget or drained:
            break
    with tel.span("device_sync"):
        jax.block_until_ready(state)
    wall_ms = (time.perf_counter() - t0) * 1e3

    if prediction is not None:
        # close the loop analytically: the manifest's prediction block
        # records what actually happened next to what was predicted
        prediction["actual_rounds"] = cur_round
        prediction["converged"] = bool(done)
        prediction["over_budget"] = over_budget
        pr = prediction.get("predicted_rounds")
        if pr:
            prediction["actual_over_predicted"] = round(cur_round / pr, 4)
        tel.event("predicted_vs_actual",
                  predicted_rounds=pr, actual_rounds=cur_round,
                  converged=bool(done), over_budget=over_budget)

    return RunResult(
        converged=done,
        rounds=cur_round,
        wall_ms=wall_ms,
        compile_ms=compile_ms,
        num_nodes=topo.num_nodes,
        algorithm=cfg.algorithm,
        # owned copies, not device_get's zero-copy views: on CPU those
        # alias XLA buffers from the donation chain, and once `state` is
        # collected the arena memory is recycled by later runs — a
        # returned result must never change value after the fact
        final_state=jax.tree.map(
            np.array, ckpt_mod.fetch_host(trim(state))
        ),
        metrics=metrics,
        checkpoints=checkpoints,
        stopped="drain" if drained else None,
    )


def run_simulation(
    topo: Topology, cfg: RunConfig, initial_state=None
) -> RunResult:
    """Build, compile, and drive the configured protocol to convergence.

    ``initial_state`` resumes from a checkpoint (SURVEY.md §5.4).
    """
    if cfg.sweep is not None:
        from gossipprotocol_tpu.sweep.engine import run_sweep

        if initial_state is not None:
            raise ValueError(
                "sweep runs cannot resume from a checkpoint — lanes have "
                "no per-lane checkpoint story yet"
            )
        return run_sweep(topo, cfg)
    run_topo = topo
    if (cfg.repair != "off" or cfg.events.has_events or cfg.quarantine_log) \
            and initial_state is not None:
        # the run's adjacency is a function of (birth topo, schedule,
        # event plan, policy, seed): replay the event rounds the
        # checkpoint already lived through so the resumed run continues
        # on the same graph bitwise (churn and repair key their rngs per
        # event round, never threaded through the run)
        from gossipprotocol_tpu.events import replay_topology

        start_round = int(np.asarray(jax.device_get(initial_state.round)))
        run_topo = replay_topology(topo, cfg, start_round)
    from gossipprotocol_tpu.obs import as_telemetry

    if cfg.payload_wire != "f32":
        raise ValueError(
            "payload_wire compresses the sharded edge-share exchange; "
            "this single-chip run has no wire — drop the flag or run "
            "with --shards"
        )
    if cfg.exchange_overlap:
        raise ValueError(
            "exchange_overlap rewrites the sharded exchange; this "
            "single-chip run has no exchange — drop the flag or run "
            "with --shards"
        )
    tel = as_telemetry(cfg.telemetry)
    with tel.span("protocol_build", engine="single-chip"):
        state, round_core, done_fn, extra_stats, (all_alive, targets_alive) = (
            build_protocol(
                run_topo, cfg,
                allow_all_alive=resume_allows_fast(topo, initial_state),
            )
        )
        if initial_state is not None:
            # copy: the chunk runner donates its input buffers, and
            # consuming the caller's arrays in-place would be a surprising
            # API
            state = jax.tree.map(jnp.array, initial_state)
    with tel.span("plan_compile", engine="single-chip"):
        nbrs = device_arrays(run_topo, cfg, tel=tel)
    base_key = jax.random.key(cfg.seed)
    # counter slots must match _drive's chunk sizing exactly (one delta
    # row per round of the largest possible chunk)
    counter_slots = cfg.resolve_chunk_rounds(
        topo.num_nodes,
        None if topo.implicit_full else int(topo.num_directed_edges),
    )

    def engine_counter_fn(ctopo, aa, ta):
        if not tel.counters_on:
            return None
        from gossipprotocol_tpu.obs.counters import make_counter_fn

        return make_counter_fn(
            ctopo, cfg, all_alive=aa, targets_alive=ta,
            interpret=(default_platform() != "tpu"),
        )

    def engine_trace_fn(ctopo):
        if not tel.traces_on:
            return None
        from gossipprotocol_tpu.obs.trace import make_trace_fn

        return make_trace_fn(ctopo, cfg)

    prediction = compute_prediction(run_topo, cfg, tel)

    rounds_per_step = cfg.rounds_per_kernel if use_megakernel(cfg) else 1
    sentinel_fn = make_sentinel_fn(cfg) if cfg.sentinel != "off" else None

    runner = make_chunk_runner(
        round_core, done_fn, extra_stats,
        counter_fn=engine_counter_fn(run_topo, all_alive, targets_alive),
        counter_slots=counter_slots,
        trace_fn=engine_trace_fn(run_topo),
        trace_slots=counter_slots,
        rounds_per_step=rounds_per_step,
        sentinel_fn=sentinel_fn,
    )

    t0 = time.perf_counter()
    with tel.span("jit_compile", engine="single-chip"):
        compiled = runner.lower(state, nbrs, base_key, jnp.int32(0)).compile()
    tel.record_compiled(
        "chunk", compiled, engine="single-chip", delivery=cfg.delivery,
        rounds_per_kernel=(rounds_per_step if use_megakernel(cfg)
                           else None),
        hub_split=(getattr(tel, "hub_split", None) or {}).get("classes"))

    def step(s, round_limit):
        return compiled(s, nbrs, base_key, jnp.int32(round_limit))

    with tel.span("warm_start"):
        state = warm_start(step, state)
    compile_ms = (time.perf_counter() - t0) * 1e3

    def rebuild(new_topo, st):
        # the repaired graph has new edge shapes: re-derive the round core
        # (keep_alive / inversion eligibility can flip with the adjacency),
        # rebuild the device neighbor arrays, recompile, and re-warm. The
        # state pytree is shape-stable (num_nodes never changes), so the
        # live buffers thread straight through.
        t0p = time.perf_counter()
        _, core2, done2, extra2, (aa2, ta2) = build_protocol(
            new_topo, cfg, allow_all_alive=False
        )
        nbrs2 = device_arrays(new_topo, cfg, tel=tel)
        plan_patch_s = time.perf_counter() - t0p
        runner2 = make_chunk_runner(
            core2, done2, extra2,
            counter_fn=engine_counter_fn(new_topo, aa2, ta2),
            counter_slots=counter_slots,
            trace_fn=engine_trace_fn(new_topo),
            trace_slots=counter_slots,
            rounds_per_step=rounds_per_step,
            sentinel_fn=sentinel_fn,
        )
        compiled2 = runner2.lower(st, nbrs2, base_key, jnp.int32(0)).compile()
        tel.record_compiled(
            "chunk_rebuild", compiled2, engine="single-chip",
            delivery=cfg.delivery,
            rounds_per_kernel=(rounds_per_step if use_megakernel(cfg)
                               else None),
            hub_split=(getattr(tel, "hub_split", None) or {}).get("classes"))

        def step2(s, round_limit):
            return compiled2(s, nbrs2, base_key, jnp.int32(round_limit))

        st = warm_start(step2, st)
        return step2, st, {"plan_patch_s": plan_patch_s}

    return _drive(topo, cfg, state, step, done_fn, compile_ms,
                  rebuild=rebuild, run_topo=run_topo, prediction=prediction)


def warm_start(step, state):
    """Execute the compiled step once with round_limit=-1 and return the
    warmed state.

    The while_loop body never runs (``s.round < -1`` is false at any
    round, including on resume), but the program is loaded onto the chip
    and the state/topology buffers are uploaded. On a tunneled TPU this
    first execution costs seconds — setup cost, not algorithm time: the
    reference's stopwatch likewise starts only after actors are spawned
    and neighbor lists delivered (``timer.Start()``, ``Program.fs:194``).
    The stats fetch is the sync point (``block_until_ready`` does not
    reliably block through the axon tunnel).
    """
    state, warm_stats = step(state, -1)
    jax.device_get(warm_stats)
    return state


def resume_simulation(topo: Topology, cfg: RunConfig, state) -> RunResult:
    """Continue a run from a checkpointed state (SURVEY.md §5.4)."""
    return run_simulation(topo, cfg, initial_state=state)


def resume_allows_fast(topo: Topology, initial_state) -> bool:
    """Can a resumed run keep the static liveness fast paths?

    Yes iff the checkpoint's dead set is exactly the birth exclusions
    (component-closed by construction) — i.e. the state a fresh run of
    this topology would start from. A checkpoint from a faulted run
    carries an arbitrary dead set; compiling out the liveness checks
    there would silently resurrect the dead.
    """
    if initial_state is None:
        return True
    alive = np.asarray(jax.device_get(initial_state.alive))
    if alive.all():
        return True
    birth = topo.birth_alive()  # host-side; no device round-trip
    return birth is not None and np.array_equal(alive[: topo.num_nodes], birth)
