"""Device mesh utilities.

The reference's "distributed" layer is an in-process actor runtime with its
remote transport never configured (SURVEY.md §2.8: Akka.Remote/DotNetty are
dead weight). The TPU-native communication backend is real: a 1-D
``jax.sharding.Mesh`` over the ``"nodes"`` axis, with node state sharded
row-wise and XLA collectives (``psum``, ``psum_scatter``, ``all_gather``)
riding ICI within a host and DCN across hosts. ``jax.distributed`` /
multi-process meshes slot in here unchanged: the mesh just spans every
process's devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODES_AXIS = "nodes"


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host run (the DCN analogue of the reference's never-
    configured Akka.Remote, SURVEY.md §2.8 — here it actually works).

    Call once per host before ``make_mesh()``; afterwards ``jax.devices()``
    spans every host, the 1-D ``"nodes"`` mesh covers all chips, and the
    same ``shard_map`` engine runs unchanged — ``psum_scatter`` rides ICI
    within a host and DCN across hosts, with XLA picking the routing.
    Arguments default to cluster auto-detection (GKE/Cloud TPU metadata);
    pass them explicitly elsewhere.
    """
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def make_mesh(
    num_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D mesh over ``num_devices`` (default: all visible) devices.

    ``num_devices`` bounds the mesh even when an explicit ``devices``
    pool is given — callers like ``run_simulation_sharded(num_devices=2,
    backend="cpu")`` hand over the backend's full device list and expect
    the count to pick the mesh size, not be silently ignored.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (NODES_AXIS,))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Row-sharded placement for a [N, ...] node-state array."""
    return NamedSharding(mesh, P(NODES_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def padded_size(n: int, num_shards: int) -> int:
    """n rounded up to a multiple of the shard count (phantom rows are
    dead-and-converged so they never influence the protocol)."""
    return ((n + num_shards - 1) // num_shards) * num_shards
