from gossipprotocol_tpu.parallel.mesh import (
    NODES_AXIS,
    initialize_distributed,
    make_mesh,
    node_sharding,
    padded_size,
    replicated,
)
from gossipprotocol_tpu.parallel.sharded import (
    make_sharded_chunk_runner,
    run_simulation_sharded,
)

__all__ = [
    "NODES_AXIS",
    "initialize_distributed",
    "make_mesh",
    "node_sharding",
    "padded_size",
    "replicated",
    "make_sharded_chunk_runner",
    "run_simulation_sharded",
]
