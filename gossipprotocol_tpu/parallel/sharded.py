"""Multi-chip engine: the round loop under ``shard_map``.

Communication design (SURVEY.md §5.8, BASELINE.json north star): node state
shards row-wise over a 1-D ``"nodes"`` mesh; the CSR adjacency is
replicated (read-only shared structure). Per round, each device

  1. draws targets for its local rows (draws key on *global* node ids, so
     trajectories are sharding-invariant — bitwise equal to single-chip),
  2. scatter-adds its contributions into a full-length partial vector
     (local ``segment_sum``), and
  3. ``psum_scatter``\\ s the partials over ICI so each device receives
     exactly its own row block — the all-reduce+slice fused into one
     reduce-scatter, the collective actually owed here (SURVEY.md §1 maps
     the reference's Akka mailbox delivery to exactly this).

The supervisor's global predicate ("counter = nodes", ``Program.fs:53``)
is a ``psum`` of per-shard unconverged counts, computed in the loop body
and carried into ``while_loop``'s cond so every shard stays in lockstep
(SURVEY.md §7 hard part e).

Padding: N rounds up to a multiple of the shard count; phantom rows are
born dead (``alive=False``) and excluded from the predicate, never drawn
as targets (no real node's neighbor list points at them), and trimmed from
everything user-visible.

The host loop (faults, metrics, checkpoints, round budget) is the same
``engine.driver._drive`` the single-chip engine uses — the engines differ
only in how one chunk step is issued.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gossipprotocol_tpu.engine.driver import (
    RunConfig,
    RunResult,
    _drive,
    build_protocol,
    compute_prediction,
    effective_keep_alive,
    mass_stats,
    warm_start,
)
from gossipprotocol_tpu.obs import as_telemetry
from gossipprotocol_tpu.parallel.mesh import (
    NODES_AXIS,
    make_mesh,
    node_sharding,
    padded_size,
    replicated,
)
from gossipprotocol_tpu.protocols.diffusion import (
    pushsum_diffusion_round_core,
    sharded_diffusion_edges,
)
from gossipprotocol_tpu.protocols.gossip import gossip_round_core
from gossipprotocol_tpu.protocols.pushsum import pushsum_round_core
from gossipprotocol_tpu.protocols.sampling import (
    DenseNeighbors,
    InvertedDense,
)
from gossipprotocol_tpu.topology.base import Topology

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    import inspect

    from jax.experimental.shard_map import shard_map as _shard_map

    if "check_vma" in inspect.signature(_shard_map).parameters:
        shard_map = _shard_map
    else:
        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
            # pre-0.6 jax spells the replication-check flag check_rep
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)


def _sharded_core(
    topo: Topology,
    cfg: RunConfig,
    all_alive: bool = False,
    targets_alive: bool = False,
    platform: str = "cpu",
):
    """The round-core factory matching build_protocol's parameters but
    using the injectable-scatter cores (collective scatter plugged in by
    the chunk body). ``platform``: the mesh devices' platform — the
    routed delivery runs its Pallas kernels natively on TPU and through
    the interpreter everywhere else (the CPU test mesh included)."""
    ref = cfg.semantics == "reference"
    n = topo.num_nodes
    # drop masks key on global ids, so the loss windows thread through the
    # sharded cores unchanged — same trajectories as single-chip
    loss_windows = cfg.schedule.static_loss_windows()
    # activation masks key on global ids too (same drop_mask primitive),
    # so the poisson clock is sharding-invariant; () = sync traces the
    # literal synchronous program
    from gossipprotocol_tpu.engine.driver import run_clock_spec

    clock = run_clock_spec(topo, cfg)
    # node-axis reduction: scalar for 1-D operands (identical jaxpr to the
    # pre-vector full sum), per-dimension [d] for vector payloads
    all_sum = lambda x: jax.lax.psum(jnp.sum(x, axis=0), NODES_AXIS)  # noqa: E731

    def wrap_workload(core):
        if cfg.workload == "sgp":
            from gossipprotocol_tpu.learn import make_sgp_core

            return make_sgp_core(
                core, lr=cfg.lr, local_steps=cfg.local_steps,
                loss_tol=cfg.loss_tol, all_sum=all_sum,
            )
        if cfg.workload == "gala":
            from gossipprotocol_tpu.learn import make_gala_core

            def group_sum(x, group_ids):
                # per-shard partial sums, all-reduced to the replicated
                # [G, ...] totals the intra-group average needs (G is
                # small — this collective is noise next to the round's)
                return jax.lax.psum(
                    jax.ops.segment_sum(
                        x, group_ids, num_segments=cfg.groups),
                    NODES_AXIS,
                )

            return make_gala_core(
                core, num_groups=cfg.groups, group_size=n // cfg.groups,
                lr=cfg.lr, local_steps=cfg.local_steps,
                loss_tol=cfg.loss_tol, all_sum=all_sum,
                group_sum=group_sum,
            )
        return core

    if cfg.algorithm == "gossip":
        from gossipprotocol_tpu.engine.driver import gossip_inversion_enabled

        return partial(
            gossip_round_core,
            n=n,
            threshold=cfg.threshold + 1 if ref else cfg.threshold,
            keep_alive=effective_keep_alive(topo, cfg),
            all_alive=all_alive,
            inverted=gossip_inversion_enabled(topo, cfg),
            all_sum=all_sum,
            loss_windows=loss_windows,
            clock=clock,
        )
    if cfg.accel != "off":
        from gossipprotocol_tpu.protocols.accel import (
            accel_round_core,
            estimate_gamma,
        )

        gamma = 0.0
        if cfg.accel == "chebyshev":
            gamma = (cfg.accel_lambda if cfg.accel_lambda is not None
                     else estimate_gamma(topo))
        return partial(
            accel_round_core,
            n=n,
            variant=cfg.accel,
            gamma=float(gamma),
            eps=cfg.eps,
            streak_target=cfg.streak_target,
            predicate=cfg.predicate,
            tol=cfg.tol,
            all_sum=all_sum,
            all_alive=all_alive,
            targets_alive=targets_alive,
            edge_chunks=cfg.edge_chunks,
        )
    if cfg.fanout == "all":
        if cfg.delivery in ("routed", "pallas"):
            # Sharded-routed delivery (the designs measured in
            # artifacts/sharded_routed_assessment.json), both with
            # per-shard plans whose capacities are forced to cross-shard
            # maxima (the shard_map single-program constraint). Default
            # "push": each shard expands only its OWNED rows and one
            # all_to_all moves the cross-shard edge shares (2·E/S·4 B
            # per shard per round, all tables O(E/S + local_n) — the
            # design that fits 100M on a v5e-8). Escape hatch "pull":
            # all_gather the full share vectors (2·n·4 B) into O(n)
            # per-shard plan_in tables.
            from gossipprotocol_tpu.ops.sharddelivery import (
                pushsum_diffusion_round_routed_push,
                pushsum_diffusion_round_routed_sharded,
            )

            push = cfg.routed_design == "push"
            kw = {}
            if push:
                # delivery='pallas' swaps the push exchange transport to
                # per-destination async remote copies (pallasdelivery.
                # pallas_exchange) — RunConfig rejects pallas+pull.
                # exchange_overlap upgrades that to the double-buffered
                # DMA ring (bitwise-equal payload bytes, overlapped
                # waits); payload_wire quantizes the edge-share slab on
                # the wire (bf16/int8) with f32 accumulation.
                if cfg.exchange_overlap:
                    kw["exchange"] = "overlap"
                else:
                    kw["exchange"] = ("pallas" if cfg.delivery == "pallas"
                                      else "all_to_all")
                kw["wire"] = cfg.payload_wire
            return wrap_workload(partial(
                pushsum_diffusion_round_routed_push
                if push
                else pushsum_diffusion_round_routed_sharded,
                n=n,
                eps=cfg.eps,
                streak_target=cfg.streak_target,
                predicate=cfg.predicate,
                tol=cfg.tol,
                all_sum=all_sum,
                all_alive=all_alive,
                targets_alive=targets_alive,
                interpret=(platform != "tpu"),
                axis_name=NODES_AXIS,
                clock=clock,
                **kw,
            ))
        return wrap_workload(partial(
            pushsum_diffusion_round_core,
            n=n,
            eps=cfg.eps,
            streak_target=cfg.streak_target,
            predicate=cfg.predicate,
            tol=cfg.tol,
            all_sum=all_sum,
            all_alive=all_alive,
            targets_alive=targets_alive,
            edge_chunks=cfg.edge_chunks,
            loss_windows=loss_windows,
            clock=clock,
        ))
    if cfg.delivery == "invert":
        raise ValueError(
            "delivery='invert' is single-chip only: the value gather needs "
            "the full (s, w) vectors local (table ids are global), which "
            "under shard_map would mean an all-gather per round — the "
            "scatter path's psum_scatter moves strictly less. Use "
            "delivery='scatter' on meshes."
        )
    if ref:
        raise ValueError(
            "semantics='reference' push-sum is the single-token walk "
            "(one MainPushSum in flight, Program.fs:128) — a serial "
            "process that cannot shard; run it single-chip (the "
            "reference is single-process anyway)"
        )
    return wrap_workload(partial(
        pushsum_round_core,
        n=n,
        eps=cfg.eps,
        streak_target=cfg.streak_target,
        reference_semantics=ref,
        predicate=cfg.predicate,
        tol=cfg.tol,
        all_sum=all_sum,
        all_alive=all_alive,
        targets_alive=targets_alive,
        loss_windows=loss_windows,
        clock=clock,
    ))


def _state_specs(state):
    """PartitionSpec pytree: [N]-arrays shard over "nodes", scalars replicate."""
    return jax.tree.map(lambda x: P(NODES_AXIS) if jnp.ndim(x) >= 1 else P(), state)


def pad_state(state, n_padded: int):
    """Pad a trimmed (real-rows) state with phantom rows: dead, converged,
    zero mass — invisible to protocol and predicate."""
    n = int(state.alive.shape[0])
    if n == n_padded:
        return state
    extra = n_padded - n

    def pad(name, x):
        if jnp.ndim(x) == 0:
            return x
        fill_shape = (extra,) + x.shape[1:]  # [n]-vectors and [n, d] payloads
        if name == "converged":
            fill = jnp.ones(fill_shape, x.dtype)
        else:  # alive -> False; counts/s/w/ratio/streak -> 0
            fill = jnp.zeros(fill_shape, x.dtype)
        return jnp.concatenate([x, fill])

    return type(state)(*(pad(f, v) for f, v in zip(type(state)._fields, state)))


def pad_neighbors(nbrs, n_padded: int):
    """Dense (and inverted-dense) tables shard row-wise with the state, so
    they pad the same way: phantom rows get degree 0 and are never sampled
    (nor counted by the inversion — ``k_valid`` masks them). CSR stays
    replicated and untouched."""
    if not isinstance(nbrs, (DenseNeighbors, InvertedDense)):
        return nbrs
    rows = int(nbrs.table.shape[0])
    if rows == n_padded:
        return nbrs
    extra = n_padded - rows

    def pad(x):
        fill_shape = (extra,) + x.shape[1:]
        return jnp.concatenate([x, jnp.zeros(fill_shape, x.dtype)])

    return type(nbrs)(*(pad(v) for v in nbrs))


def make_sharded_chunk_runner(
    topo: Topology, cfg: RunConfig, mesh: Mesh, allow_all_alive: bool = True,
    nbrs_override=None, counter_slots: Optional[int] = None,
    lane_cfgs=None,
):
    """jitted ``(state, nbrs, seed, round_limit) -> state`` advancing one
    chunk under shard_map. Returns (runner, initial padded+placed state,
    placed nbrs, done_fn).

    ``nbrs_override``: pre-built routed shard deliveries to use instead
    of the plan-cache path — the repair engine hands in incrementally
    *patched* plans here (ops/sharddelivery.py), which must never reach
    the cache: their capacities are forced to the pre-repair maxima, so
    a cold build of the same topology would produce different tables.

    ``counter_slots``: when ``cfg.telemetry`` has counters on, the rows
    of the per-chunk message-counter buffer — must cover ``_drive``'s
    chunk sizing for the *birth* topology (``run_simulation_sharded``
    passes it; a repaired topology can resolve a different chunk size,
    and a too-small buffer would silently clamp delta rows together).
    ``lane_cfgs``: per-lane RunConfigs for a vmapped mega-sweep
    (sweep/engine.py). The shard_map'd chunk is left byte-identical —
    lanes compose as ``jax.vmap`` OUTSIDE it over (state, seed), so the
    per-lane program inside the mesh is the literal sharded chunk and
    inherits its single-chip-equal contract. Only host-consumed axes
    (seed, seed_node) may differ between the lane configs; the sweep
    engine validates that before calling."""
    n = topo.num_nodes
    num_shards = int(mesh.devices.size)
    n_padded = padded_size(n, num_shards)
    local_n = n_padded // num_shards
    tel = as_telemetry(cfg.telemetry)

    # build_protocol's flag pair is the single source of truth for the
    # liveness fast paths (padding rows are handled there via num_rows;
    # they are never anyone's target, so targets_alive tolerates them)
    state0, _, done_fn, _, (all_alive, targets_alive) = build_protocol(
        topo, cfg, num_rows=n_padded, allow_all_alive=allow_all_alive
    )
    platform = mesh.devices.flat[0].platform
    core = _sharded_core(
        topo, cfg, all_alive=all_alive, targets_alive=targets_alive,
        platform=platform,
    )
    is_pushsum = cfg.algorithm != "gossip"
    sentinel_on = cfg.sentinel != "off"
    routed = (is_pushsum and cfg.fanout == "all"
              and cfg.delivery in ("routed", "pallas"))
    if hasattr(topo, "csr_slice"):
        # a streamed out-of-core build carries per-shard CSR slices only;
        # the global adjacency never exists. The routed plan builders
        # consume slices natively — every other delivery assembles global
        # edge/neighbor tables, so reject with the fix here instead of
        # an AttributeError deep in table assembly.
        if not routed:
            raise ValueError(
                "a streamed topology build (per-shard CSR slices, no "
                "global adjacency) supports the sharded routed designs "
                "only (push-sum, --fanout all, --delivery routed/pallas)"
                " — use --build materialized for this config")
        if topo.num_shards != num_shards:
            raise ValueError(
                f"streamed build is partitioned for {topo.num_shards} "
                f"shards but the mesh has {num_shards} devices — "
                "rebuild with a matching --devices")
        if topo.n_padded != n_padded:
            raise ValueError(
                f"streamed build padded rows to {topo.n_padded}, the "
                f"mesh wants {n_padded} — partition mismatch")
    psum_all = lambda x: jax.lax.psum(jnp.sum(x, axis=0), NODES_AXIS)  # noqa: E731
    counter_fn = None
    if tel.counters_on:
        from gossipprotocol_tpu.obs.counters import make_counter_fn

        counter_fn = make_counter_fn(
            topo, cfg, all_alive=all_alive, targets_alive=targets_alive,
            all_sum=psum_all, interpret=(platform != "tpu"),
            axis_name=NODES_AXIS,
        )
    trace_fn = None
    if tel.traces_on:
        # same replication contract as the counters: every row component
        # is psum/pmax-reduced inside the fn, so the buffer spec stays P()
        from gossipprotocol_tpu.obs.trace import make_trace_fn

        trace_fn = make_trace_fn(
            topo, cfg, all_sum=psum_all,
            all_max=lambda x: jax.lax.pmax(jnp.max(x), NODES_AXIS),
        )
    # per-device attribution: keep the counter partials unreduced per
    # shard alongside the psum'd buffer. Off keeps this function's jaxpr
    # literally pre-attribution (the goldens pin it), and on never feeds
    # back into the round, so the trajectory is bitwise identical.
    attribution = counter_fn is not None and tel.attribution_on
    if (counter_fn is not None or trace_fn is not None) \
            and counter_slots is None:
        counter_slots = cfg.resolve_chunk_rounds(
            n, None if topo.implicit_full else int(topo.num_directed_edges)
        )

    def chunk_local(state_l, nbrs, seed, round_limit):
        base_key = jax.random.key(seed)
        shard = jax.lax.axis_index(NODES_AXIS)
        gids = shard * local_n + jnp.arange(local_n, dtype=jnp.int32)
        # faults only strike between chunks, so the global aliveness mask
        # is loop-invariant within a chunk: gather it once. Only the
        # push-sum general path ever reads it — gossip suppresses on the
        # receiver side and the fast paths compile the lookup away.
        alive_g = (
            None if targets_alive or not is_pushsum
            else jax.lax.all_gather(state_l.alive, NODES_AXIS, tiled=True)
        )

        def scatter1(v, t):
            full = jax.ops.segment_sum(v, t, num_segments=n_padded)
            return jax.lax.psum_scatter(
                full, NODES_AXIS, scatter_dimension=0, tiled=True
            )

        def scatter2(a, b, t):
            # two 1-D scatters, NOT one [N,2] scatter: XLA's TPU scatter on
            # a stacked operand costs ~3x two flat ones (measured at 1M);
            # results stack only for the single fused collective
            fa = jax.ops.segment_sum(a, t, num_segments=n_padded)
            fb = jax.ops.segment_sum(b, t, num_segments=n_padded)
            if a.ndim == 1:
                loc = jax.lax.psum_scatter(
                    jnp.stack([fa, fb], axis=1), NODES_AXIS,
                    scatter_dimension=0, tiled=True,
                )
                return loc[:, 0], loc[:, 1]
            # vector payload: fa is [N, d] — ride the d payload columns and
            # the weight column through the same single fused collective
            loc = jax.lax.psum_scatter(
                jnp.concatenate([fa, fb[:, None]], axis=1), NODES_AXIS,
                scatter_dimension=0, tiled=True,
            )
            return loc[:, :-1], loc[:, -1]

        if routed:
            # the stacked shard-delivery leaves arrive as this device's
            # [1, ...] slice; the round core drops the axis itself. The
            # SGP/GALA wrapper rides the bundle in its generic nbrs slot
            # and forwards bundle.nbrs to the mix core positionally
            if cfg.workload in ("sgp", "gala"):
                round_fn = partial(core, nbrs=nbrs, base_key=base_key)
            else:
                round_fn = partial(core, shard_rd=nbrs, base_key=base_key)
        elif is_pushsum and cfg.fanout == "all":
            # diffusion: no draws, no gids — edges are pre-localized by
            # source block, delivery is the same scatter2 collective.
            # row_offset re-globalizes the local src ids so per-edge drop
            # masks key on (global src, global dst) — sharding-invariant
            round_fn = partial(
                core, nbrs=nbrs, base_key=base_key,
                scatter=scatter2, alive_global=alive_g,
                row_offset=shard * local_n,
            )
        elif is_pushsum:
            round_fn = partial(
                core, nbrs=nbrs, base_key=base_key, gids=gids,
                scatter=scatter2, alive_global=alive_g,
            )
        else:
            round_fn = partial(
                core, nbrs=nbrs, base_key=base_key, gids=gids, scatter=scatter1,
            )

        if cfg.alert_quorum is not None:
            # quorum supervisor (reference's N+1 population, see
            # build_protocol): padding rows are pre-settled and shift
            # the threshold — identical rule to the single-chip engine
            quorum_eff = cfg.alert_quorum + (n_padded - n)

            def global_done(s):
                settled = jnp.sum(
                    (s.converged | ~s.alive).astype(jnp.int32))
                return jax.lax.psum(settled, NODES_AXIS) >= quorum_eff
        else:
            def global_done(s):
                unconv = jnp.sum((~s.converged & s.alive).astype(jnp.int32))
                return jax.lax.psum(unconv, NODES_AXIS) == 0

        if sentinel_on:
            # the loop stops when any shard holds a sick row: psum the
            # local any() so every shard exits the same iteration (the
            # cond must agree collectively, like global_done itself).
            # Off leaves loop_stop as the literal global_done function
            # object — the traced program is byte-identical (the goldens
            # pin it).
            from gossipprotocol_tpu.engine.driver import sentinel_bad_mask

            def global_trip(s):
                bad = jnp.any(sentinel_bad_mask(s)).astype(jnp.int32)
                return jax.lax.psum(bad, NODES_AXIS) > 0

            def loop_stop(s):
                return jnp.logical_or(global_done(s), global_trip(s))
        else:
            loop_stop = global_done

        if counter_fn is None and trace_fn is None:
            def body(carry):
                s, _ = carry
                s = round_fn(s)
                return s, loop_stop(s)

            def cond(carry):
                s, done = carry
                return jnp.logical_and(~done, s.round < round_limit)

            final, done = jax.lax.while_loop(
                cond, body, (state_l, loop_stop(state_l))
            )
            buf = None
            sbuf = None
            trace_buf = None
        elif trace_fn is not None:
            # traces (optionally + counters): per-round side buffers in a
            # dict carry. Every buffer row is psum/pmax-replicated by
            # construction, and neither buffer ever feeds back into the
            # round, so the state trajectory is bitwise the no-telemetry
            # one (same contract as the counter-only branch below).
            from gossipprotocol_tpu.obs.trace import NUM_TRACE_COLS

            start = state_l.round

            def body(carry):
                s, _, bufs = carry
                s2 = round_fn(s)
                row = s.round - start
                bufs = dict(bufs)
                if counter_fn is not None:
                    alive_cnt = alive_g if alive_g is not None else s.alive
                    raw = counter_fn(s, s2, nbrs, base_key, alive_cnt, gids)
                    delta = jax.lax.psum(raw, NODES_AXIS)
                    bufs["counters"] = jax.lax.dynamic_update_slice(
                        bufs["counters"], delta[None, :],
                        (row, jnp.int32(0)))
                    if attribution:
                        bufs["shard_counters"] = jax.lax.dynamic_update_slice(
                            bufs["shard_counters"], raw[None, :],
                            (row, jnp.int32(0)))
                bufs["trace"] = jax.lax.dynamic_update_slice(
                    bufs["trace"],
                    trace_fn(s2).astype(jnp.float32)[None, :],
                    (row, jnp.int32(0)))
                return s2, loop_stop(s2), bufs

            def cond(carry):
                s, done, _ = carry
                return jnp.logical_and(~done, s.round < round_limit)

            bufs0 = {"trace": jnp.zeros(
                (counter_slots, NUM_TRACE_COLS), jnp.float32)}
            if counter_fn is not None:
                bufs0["counters"] = jnp.zeros((counter_slots, 3), jnp.int32)
                if attribution:
                    bufs0["shard_counters"] = jnp.zeros(
                        (counter_slots, 3), jnp.int32)
            final, done, bufs = jax.lax.while_loop(
                cond, body, (state_l, loop_stop(state_l), bufs0)
            )
            buf = bufs.get("counters")
            sbuf = bufs.get("shard_counters")
            trace_buf = bufs["trace"]
        elif attribution:
            # counters + per-shard attribution: the same psum'd buffer
            # plus the unreduced partials (this shard's own rows; the
            # P(NODES_AXIS) out spec concatenates shards leading-axis).
            # raw -> psum(raw) is the identical reduction the plain
            # branch below compiles, so the psum'd stream stays bitwise.
            start = state_l.round

            def body(carry):
                s, _, bufs = carry
                alive_cnt = alive_g if alive_g is not None else s.alive
                s2 = round_fn(s)
                raw = counter_fn(s, s2, nbrs, base_key, alive_cnt, gids)
                delta = jax.lax.psum(raw, NODES_AXIS)
                row = s.round - start
                bufs = dict(bufs)
                bufs["counters"] = jax.lax.dynamic_update_slice(
                    bufs["counters"], delta[None, :], (row, jnp.int32(0)))
                bufs["shard_counters"] = jax.lax.dynamic_update_slice(
                    bufs["shard_counters"], raw[None, :],
                    (row, jnp.int32(0)))
                return s2, loop_stop(s2), bufs

            def cond(carry):
                s, done, _ = carry
                return jnp.logical_and(~done, s.round < round_limit)

            bufs0 = {
                "counters": jnp.zeros((counter_slots, 3), jnp.int32),
                "shard_counters": jnp.zeros((counter_slots, 3), jnp.int32),
            }
            final, done, bufs = jax.lax.while_loop(
                cond, body, (state_l, loop_stop(state_l), bufs0)
            )
            buf = bufs["counters"]
            sbuf = bufs["shard_counters"]
            trace_buf = None
        else:
            # telemetry counters: per-round int32 deltas in a side buffer
            # (row = round − chunk start). The counter fn re-derives the
            # round's draws without touching state or PRNG stream, and the
            # per-round psum replicates the deltas so the stats spec stays
            # P(). The state trajectory is bitwise identical either way.
            start = state_l.round

            def body(carry):
                s, _, buf = carry
                alive_cnt = alive_g if alive_g is not None else s.alive
                s2 = round_fn(s)
                delta = jax.lax.psum(
                    counter_fn(s, s2, nbrs, base_key, alive_cnt, gids),
                    NODES_AXIS,
                )
                buf = jax.lax.dynamic_update_slice(
                    buf, delta[None, :], (s.round - start, jnp.int32(0)))
                return s2, loop_stop(s2), buf

            def cond(carry):
                s, done, _ = carry
                return jnp.logical_and(~done, s.round < round_limit)

            buf0 = jnp.zeros((counter_slots, 3), jnp.int32)
            final, done, buf = jax.lax.while_loop(
                cond, body, (state_l, loop_stop(state_l), buf0)
            )
            sbuf = None
            trace_buf = None
        # replicated on-device stats: one host fetch per chunk (mirrors
        # engine.driver.chunk_stats, with psum/pmin/pmax reductions)
        stats = {
            "round": final.round,
            "done": done,
            "converged": jax.lax.psum(
                jnp.sum((final.converged & final.alive).astype(jnp.int32)),
                NODES_AXIS,
            ),
            "alive": jax.lax.psum(
                jnp.sum(final.alive.astype(jnp.int32)), NODES_AXIS
            ),
        }
        if is_pushsum:
            big = jnp.asarray(jnp.inf, final.ratio.dtype)
            live = (final.alive if final.ratio.ndim == 1
                    else final.alive[:, None])
            stats["ratio_min"] = jax.lax.pmin(
                jnp.min(jnp.where(live, final.ratio, big)), NODES_AXIS
            )
            stats["ratio_max"] = jax.lax.pmax(
                jnp.max(jnp.where(live, final.ratio, -big)), NODES_AXIS
            )
            if hasattr(final, "loss"):
                stats["train_loss"] = final.loss  # psum-replicated already
            # mirrors chunk_stats' dry-spell underflow detector
            stats["w_underflow"] = jax.lax.psum(
                jnp.sum((final.alive & (final.w == 0)).astype(jnp.int32)),
                NODES_AXIS,
            )
        else:
            from gossipprotocol_tpu.engine.driver import gossip_spreading_count

            stats["spreading"] = jax.lax.psum(
                gossip_spreading_count(
                    final, effective_keep_alive(topo, cfg)), NODES_AXIS
            )
        if counter_fn is not None:
            stats["counters"] = buf  # already psum-replicated per round
            if sbuf is not None:
                stats["shard_counters"] = sbuf  # per-shard, NOT replicated
            # conservation scalars: same reduction for baseline and chunk
            # (mass_stats docstring) — psum of local sums under shard_map
            stats.update(mass_stats(final, all_sum=psum_all))
        if trace_buf is not None:
            stats["trace"] = trace_buf  # psum/pmax-replicated per round
        if sentinel_on:
            # the carried flag is loop_stop (done | trip): report real
            # convergence separately, and surface the trip so the host
            # can attribute rows at the chunk boundary. Mass scalars
            # feed the host ULP tripwire — dedup with the counter path.
            stats["done"] = global_done(final)
            stats["sentinel_trip"] = jax.lax.psum(
                jnp.any(sentinel_bad_mask(final)).astype(jnp.int32),
                NODES_AXIS,
            )
            if "mass_s" not in stats:
                stats.update(mass_stats(final, all_sum=psum_all))
        return final, stats

    specs = _state_specs(state0)
    if routed:
        if nbrs_override is not None:
            nbrs = nbrs_override
        else:
            from gossipprotocol_tpu.ops import plancache, sharddelivery

            if cfg.routed_design == "push":
                nbrs, prov = plancache.shard_push_deliveries_cached(
                    topo, n_padded, num_shards, cache_dir=cfg.plan_cache,
                    build_workers=cfg.build_workers)
                exch = sharddelivery.push_exchange_wire_bytes_per_round(
                    nbrs, cfg.payload_wire)
            else:
                nbrs, prov = plancache.shard_deliveries_cached(
                    topo, n_padded, num_shards, cache_dir=cfg.plan_cache,
                    build_workers=cfg.build_workers)
                exch = sharddelivery.pull_exchange_bytes_per_round(nbrs)
            tel.event(
                "plan_cache", provenance=prov, design=cfg.routed_design,
                num_shards=num_shards, exchange_bytes_per_round=exch,
            )
            tel.note_resource("exchange_bytes_per_round", exch)
            tel.note_resource(
                "routed_table_bytes", sharddelivery.table_bytes(nbrs))
        nbrs_sharded = True  # leading shard axis splits over the mesh
    elif is_pushsum and cfg.fanout == "all":
        # every leaf of the edge pytree is built as equal per-device
        # blocks (edges by source block, degree row-aligned) -> all shard
        nbrs = sharded_diffusion_edges(topo, n_padded, num_shards)
        nbrs_sharded = nbrs is not None  # None = implicit complete graph
    else:
        import dataclasses as _dc

        from gossipprotocol_tpu.engine.driver import device_arrays

        # SGP wraps the delivery pytree in a bundle; build the bare
        # delivery here and wrap below, so padding/sharding of the
        # neighbor tables stays on this one path
        inner_cfg = (_dc.replace(cfg, workload="avg", groups=1)
                     if cfg.workload in ("sgp", "gala") else cfg)
        nbrs = pad_neighbors(device_arrays(topo, inner_cfg), n_padded)
        # dense adjacency rows align with the state rows -> shard over
        # "nodes" (each device holds only its own rows); CSR replicates
        # (its flat index pool can't split along node boundaries)
        nbrs_sharded = isinstance(nbrs, (DenseNeighbors, InvertedDense))
    nbrs_specs = jax.tree.map(
        lambda _: P(NODES_AXIS) if nbrs_sharded else P(), nbrs
    )
    sgp_bundle = is_pushsum and cfg.workload in ("sgp", "gala")
    if sgp_bundle:
        from gossipprotocol_tpu.learn import SGPBundle, make_least_squares

        a, b, _ = make_least_squares(
            n, cfg.payload_dim, cfg.sgp_samples, cfg.seed,
            dtype=np.dtype(jnp.dtype(cfg.dtype).name), rows=n_padded,
        )
        # data rows shard with the state rows; the inner delivery keeps
        # its own placement (mixed specs within the bundle pytree)
        if nbrs is not None:
            nbrs = jax.device_put(
                nbrs,
                node_sharding(mesh) if nbrs_sharded else replicated(mesh),
            )
        data_sh = node_sharding(mesh)
        nbrs = SGPBundle(
            nbrs=nbrs,
            A=jax.device_put(jnp.asarray(a), data_sh),
            b=jax.device_put(jnp.asarray(b), data_sh),
        )
        nbrs_specs = SGPBundle(
            nbrs=nbrs_specs, A=P(NODES_AXIS), b=P(NODES_AXIS))

    stats_fields = ["round", "done", "converged", "alive"]
    if cfg.algorithm != "gossip":
        stats_fields += ["ratio_min", "ratio_max", "w_underflow"]
        if cfg.workload in ("sgp", "gala"):
            stats_fields += ["train_loss"]
    else:
        stats_fields += ["spreading"]
    if counter_fn is not None:
        stats_fields += ["counters"]
        if attribution:
            stats_fields += ["shard_counters"]
        if is_pushsum and cfg.workload not in ("sgp", "gala"):
            # SGP/GALA inject mass every round by design; mass_stats
            # returns nothing for them (see engine.driver.mass_stats)
            stats_fields += ["mass_s", "mass_w"]
    if trace_fn is not None:
        stats_fields += ["trace"]
    if sentinel_on:
        stats_fields += ["sentinel_trip"]
        if (is_pushsum and cfg.workload not in ("sgp", "gala")
                and "mass_s" not in stats_fields):
            stats_fields += ["mass_s", "mass_w"]
    stats_specs = {k: P() for k in stats_fields}
    if attribution:
        # the one unreduced stat: per-shard [slots, 3] partials gathered
        # to [num_shards * slots, 3] on the host side
        stats_specs["shard_counters"] = P(NODES_AXIS)
    sm = shard_map(
        chunk_local,
        mesh=mesh,
        in_specs=(specs, nbrs_specs, P(), P()),
        out_specs=(specs, stats_specs),
        check_vma=False,
    )
    if lane_cfgs is not None:
        # mega-sweep: vmap the UNCHANGED shard_map'd chunk over a leading
        # lane axis of (state, seed). Per-lane initial states re-run
        # build_protocol (seed_node draws differ per lane); nbrs and
        # round_limit broadcast. The while_loop batching rule freezes a
        # done lane's whole carry bitwise while others keep running.
        lane_states = [
            build_protocol(topo, lc, num_rows=n_padded,
                           allow_all_alive=allow_all_alive)[0]
            for lc in lane_cfgs
        ]
        state0 = jax.tree.map(lambda *xs: jnp.stack(xs), *lane_states)
        lane_specs = jax.tree.map(
            lambda s: P(*((None,) + tuple(s))), specs)
        runner = jax.jit(
            jax.vmap(sm, in_axes=(0, None, 0, None)), donate_argnums=0)
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), lane_specs)
        state0 = jax.device_put(state0, shardings)
        if nbrs is not None and not sgp_bundle:
            nbrs = jax.device_put(
                nbrs,
                node_sharding(mesh) if nbrs_sharded else replicated(mesh),
            )
        return runner, state0, nbrs, done_fn, shardings
    runner = jax.jit(sm, donate_argnums=0)

    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
    state0 = jax.device_put(state0, shardings)
    if nbrs is not None and not sgp_bundle:  # bundle placed piecewise above
        nbrs = jax.device_put(
            nbrs, node_sharding(mesh) if nbrs_sharded else replicated(mesh)
        )
    return runner, state0, nbrs, done_fn, shardings


def run_simulation_sharded(
    topo: Topology,
    cfg: RunConfig,
    num_devices: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    initial_state=None,
    backend: Optional[str] = None,
) -> RunResult:
    """Multi-chip ``run_simulation``: same semantics, same trajectories,
    state sharded over the mesh.

    Invariance contract: per-node draws key on global ids, so every mesh
    size samples identical targets. Gossip state is integer and therefore
    bitwise-identical to single-chip. Push-sum values match up to float
    accumulation order (per-device partial scatters + ``psum_scatter``
    associate differently than one global scatter), i.e. to ~ulp — which
    the eps-streak predicate can amplify into slightly different round
    counts on threshold-crossing rounds.

    ``initial_state`` resumes from a (trimmed) checkpoint: it is re-padded
    to the mesh and takes over from its recorded round.
    """
    if cfg.sweep is not None:
        from gossipprotocol_tpu.sweep.engine import run_sweep_sharded

        if initial_state is not None:
            raise ValueError(
                "sweep runs cannot resume from a checkpoint — lanes have "
                "no per-lane checkpoint story yet"
            )
        return run_sweep_sharded(
            topo, cfg, num_devices=num_devices, mesh=mesh, backend=backend)
    from gossipprotocol_tpu.engine.driver import use_megakernel

    if use_megakernel(cfg):
        raise ValueError(
            "the round-loop megakernel is single-chip only (the in-kernel "
            "round has no exchange step) — drop --shards, or use "
            "--delivery pallas with rounds_per_kernel=1"
        )
    if mesh is None:
        devices = jax.devices(backend) if backend else None
        mesh = make_mesh(num_devices, devices=devices)
    n = topo.num_nodes
    num_shards = int(mesh.devices.size)
    n_padded = padded_size(n, num_shards)

    if hasattr(topo, "csr_slice"):
        if cfg.repair != "off" or cfg.events.has_events:
            # the event/repair engine rewrites the *global* adjacency
            # (replay_topology, plan patching), which a streamed build
            # never materializes; delivery compatibility itself is
            # checked in make_sharded_chunk_runner
            raise ValueError(
                "event/repair schedules rewrite the global adjacency, "
                "which a streamed build never materializes — use "
                "--build materialized with event plans")
        if cfg.sentinel in ("quarantine", "rollback"):
            # quarantine fires a synthetic kill through the same engine
            # (partition rule + optional repair need the global CSR)
            raise ValueError(
                "sentinel quarantine/rollback kills nodes through the "
                "event engine, which needs the global adjacency a "
                "streamed build never materializes — use --build "
                "materialized, or --sentinel on for detection only")
        if topo.num_shards != num_shards:
            # checked before the routed-push plan pre-build below, which
            # would otherwise fail on a misaligned csr_slice request
            raise ValueError(
                f"streamed build is partitioned for {topo.num_shards} "
                f"shards but the mesh has {num_shards} devices — "
                "rebuild with a matching --devices")

    from gossipprotocol_tpu.engine.driver import resume_allows_fast

    run_topo = topo
    if (cfg.repair != "off" or cfg.events.has_events or cfg.quarantine_log) \
            and initial_state is not None:
        # same replay the single-chip engine does: the resumed run must
        # continue on the adjacency the checkpoint lived through (repair
        # and churn events alike)
        from gossipprotocol_tpu.events import replay_topology

        start_round = int(np.asarray(jax.device_get(initial_state.round)))
        run_topo = replay_topology(topo, cfg, start_round)

    is_pushsum = cfg.algorithm != "gossip"
    routed = (is_pushsum and cfg.fanout == "all"
              and cfg.delivery in ("routed", "pallas"))
    routed_push = routed and cfg.routed_design == "push"
    tel = as_telemetry(cfg.telemetry)
    # counter-buffer rows must cover _drive's chunk sizing, which is
    # computed from the BIRTH topology (run_topo may be a repair replay)
    counter_slots = cfg.resolve_chunk_rounds(
        n, None if topo.implicit_full else int(topo.num_directed_edges)
    )
    # for routed-push repair runs, hold the host-side stacked plans: the
    # incremental patcher splices rebuilt shards into them at repair events
    plans_host = None
    if routed_push:
        from gossipprotocol_tpu.ops import plancache, sharddelivery

        with tel.span("plan_compile", engine="sharded"):
            plans_host, prov = plancache.shard_push_deliveries_cached(
                run_topo, n_padded, num_shards, cache_dir=cfg.plan_cache,
                build_workers=cfg.build_workers)
        exch = sharddelivery.push_exchange_wire_bytes_per_round(
            plans_host, cfg.payload_wire)
        tel.event(
            "plan_cache", provenance=prov, design="push",
            num_shards=num_shards, exchange_bytes_per_round=exch,
        )
        tel.note_resource("exchange_bytes_per_round", exch)
        tel.note_resource(
            "routed_table_bytes", sharddelivery.table_bytes(plans_host))

    with tel.span("topology_arrays", engine="sharded"):
        runner, state, nbrs, done_fn, shardings = make_sharded_chunk_runner(
            run_topo, cfg, mesh,
            allow_all_alive=resume_allows_fast(topo, initial_state),
            nbrs_override=plans_host, counter_slots=counter_slots,
        )
    if initial_state is not None:
        # copy before placing: device_put of host numpy arrays is
        # zero-copy on CPU, and the chunk runner donates its inputs —
        # consuming the caller's checkpoint arrays in-place would be a
        # surprising API
        owned = jax.tree.map(np.array, pad_state(initial_state, n_padded))
        state = jax.device_put(owned, shardings)
    seed = jnp.int32(cfg.seed)

    t0 = time.perf_counter()
    with tel.span("jit_compile", engine="sharded"):
        compiled = runner.lower(state, nbrs, seed, jnp.int32(0)).compile()
    if routed and not run_topo.implicit_full:
        from gossipprotocol_tpu.engine.driver import note_hub_split

        note_hub_split(tel, run_topo)
    tel.record_compiled(
        "chunk", compiled, engine="sharded", num_shards=num_shards,
        delivery=cfg.delivery,
        payload_wire=(cfg.payload_wire if cfg.payload_wire != "f32"
                      else None),
        hub_split=(getattr(tel, "hub_split", None) or {}).get("classes"))

    def step(s, round_limit):
        return compiled(s, nbrs, seed, jnp.int32(round_limit))

    with tel.span("warm_start"):
        state = warm_start(step, state)
    compile_ms = (time.perf_counter() - t0) * 1e3

    def trim(s):
        return jax.tree.map(lambda x: x[:n] if jnp.ndim(x) >= 1 else x, s)

    cur = {"topo": run_topo, "plans": plans_host}

    def rebuild(new_topo, st):
        # repair-event rebuild: patch the routed plans incrementally when
        # possible (only the shards whose owned CSR slice changed pay the
        # heavy routing pass), re-derive the shard_map program, recompile,
        # re-warm. State shapes/shardings are stable (n_padded fixed).
        info: dict = {}
        nbrs_over = None
        if routed:
            from gossipprotocol_tpu.ops import sharddelivery as sd

            t0p = time.perf_counter()
            if routed_push and cur["plans"] is not None:
                patched = sd.patch_shard_push_deliveries(
                    cur["topo"], new_topo, cur["plans"], n_padded,
                    num_shards, build_workers=cfg.build_workers)
                if patched is not None:
                    nbrs_over, rebuilt = patched
                    info = {"plan_patch": "incremental",
                            "plan_shards_rebuilt": int(rebuilt)}
            if nbrs_over is None:
                # pull design, or the patch preconditions failed (the
                # repaired census outgrew the forced capacities): cold
                # build, bypassing the cache — per-event topologies
                # would bloat it for a one-shot use
                if routed_push:
                    nbrs_over = sd.build_shard_push_deliveries(
                        new_topo, n_padded, num_shards,
                        build_workers=cfg.build_workers)
                else:
                    nbrs_over = sd.build_shard_deliveries(
                        new_topo, n_padded, num_shards,
                        build_workers=cfg.build_workers)
                info = {"plan_patch": "cold",
                        "plan_shards_rebuilt": num_shards}
            info["plan_patch_s"] = time.perf_counter() - t0p
        runner2, _, nbrs2, _, _ = make_sharded_chunk_runner(
            new_topo, cfg, mesh, allow_all_alive=False,
            nbrs_override=nbrs_over, counter_slots=counter_slots,
        )
        compiled2 = runner2.lower(st, nbrs2, seed, jnp.int32(0)).compile()
        tel.record_compiled(
            "chunk_rebuild", compiled2, engine="sharded",
            num_shards=num_shards, delivery=cfg.delivery,
            payload_wire=(cfg.payload_wire if cfg.payload_wire != "f32"
                          else None))

        def step2(s, round_limit):
            return compiled2(s, nbrs2, seed, jnp.int32(round_limit))

        st = warm_start(step2, st)
        cur["topo"], cur["plans"] = new_topo, nbrs_over if routed_push else None
        return step2, st, info

    def reload_fn(st):
        # rollback re-materialization: same copy-then-place discipline as
        # the resume path above (the runner donates its inputs)
        owned = jax.tree.map(np.array, pad_state(st, n_padded))
        return jax.device_put(owned, shardings)

    return _drive(topo, cfg, state, step, done_fn, compile_ms, trim=trim,
                  rebuild=rebuild, run_topo=run_topo,
                  prediction=compute_prediction(run_topo, cfg, tel),
                  reload_fn=reload_fn)
