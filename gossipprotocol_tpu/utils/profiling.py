"""Tracing / profiling hooks (SURVEY.md §5.1).

The reference's only instrument is one ``Stopwatch`` around the whole run
(``Program.fs:35,194,54``) — covered here by the driver's compile-vs-run
separation and round counts. This module adds the optional
``jax.profiler`` trace context so a run can be inspected in
TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def maybe_trace(trace_dir: str | None):
    """``jax.profiler.trace`` when a directory is given; no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
