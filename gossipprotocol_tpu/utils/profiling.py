"""Tracing / profiling hooks (SURVEY.md §5.1).

The reference's only instrument is one ``Stopwatch`` around the whole run
(``Program.fs:35,194,54``). Here: the driver already separates compile time
from run time and counts rounds; this module adds an optional
``jax.profiler`` trace context so a run can be inspected in
TensorBoard/Perfetto, plus a tiny stopwatch helper for host-side phases.
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def maybe_trace(trace_dir: str | None):
    """``jax.profiler.trace`` when a directory is given; no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


class Stopwatch:
    """Reference-style stopwatch (``Program.fs:35``), host-side, ms units."""

    def __init__(self):
        self._t0 = None
        self.elapsed_ms = 0.0

    def start(self):
        self._t0 = time.perf_counter()
        return self

    def stop(self) -> float:
        if self._t0 is not None:
            self.elapsed_ms += (time.perf_counter() - self._t0) * 1e3
            self._t0 = None
        return self.elapsed_ms
