from gossipprotocol_tpu.utils import checkpoint, faults, metrics, profiling

__all__ = ["checkpoint", "faults", "metrics", "profiling"]
