"""splitmix64 — the shared host-side RNG for graph construction.

Both the numpy topology builders and the native C++ graph builder
(``native/graphgen.cpp``) draw from this exact counter-based generator, so
a topology built with either backend is bitwise identical: same seed, same
graph, same simulation trajectory. (The *device-side* protocol RNG is
jax.random/threefry and unrelated.)

splitmix64 reference: Steele, Lea & Flood, "Fast splittable pseudorandom
number generators" (the public-domain mix function used by java.util
.SplittableRandom and most C++ seeding utilities).
"""

from __future__ import annotations

import numpy as np

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def splitmix64(seed: int, counters: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64: hash of (seed, counter) per element.

    counters: uint64 array (any shape). Returns uint64 of same shape.
    """
    seed = np.uint64(int(seed) & (2**64 - 1))  # mask like the C++ uint64_t
    with np.errstate(over="ignore"):
        x = (seed + (counters.astype(np.uint64) + np.uint64(1)) * _GOLDEN)
        x = (x ^ (x >> np.uint64(30))) * _M1
        x = (x ^ (x >> np.uint64(27))) * _M2
        x = x ^ (x >> np.uint64(31))
    return x


def uniform_int(seed: int, counters: np.ndarray, bound: int) -> np.ndarray:
    """Draws in [0, bound) — modulo map (bias < bound/2⁶⁴, negligible)."""
    return (splitmix64(seed, counters) % np.uint64(bound)).astype(np.int64)
