"""Checkpoint / resume (SURVEY.md §5.4).

The reference keeps all state in actor memory and discards it with
``Environment.Exit(0)`` (``Program.fs:56``). Here the entire system state is
a small pytree of dense arrays, so a checkpoint is one compressed npz file:
state arrays + enough config metadata to validate a resume. Orbax is
unnecessary at this state size (a 10M-node push-sum state is ~200 MB); npz
keeps checkpoints dependency-free and host-portable.
"""

from __future__ import annotations

import json
import os
import time
from typing import Tuple

import jax
import numpy as np

from gossipprotocol_tpu.protocols.state import (
    AccelState,
    GossipState,
    PushSumState,
    SGPState,
)
from gossipprotocol_tpu.protocols.walk import WalkState

_STATE_TYPES = {"GossipState": GossipState, "PushSumState": PushSumState,
                "WalkState": WalkState, "SGPState": SGPState,
                "AccelState": AccelState}

# Every RunConfig field that influences the trajectory. Saved in checkpoint
# metadata and compared generically on resume — resuming under a different
# convergence rule (or PRNG seed) would continue on a plausible-looking but
# different run, which must be an error, not a silent acceptance.
TRAJECTORY_FIELDS = (
    "algorithm", "seed", "semantics", "threshold", "eps", "streak_target",
    "keep_alive", "predicate", "tol", "value_mode", "dtype",
    # the stop rule is part of the trajectory: splicing a quorum run onto
    # an all-nodes run (or vice versa) would change when the world stops
    "alert_quorum",
    # sender/delivery variants change the trajectory too: fanout="all" is a
    # different protocol; delivery="invert" sums received mass in a
    # different float order than the scatter (both docstrings say so)
    "fanout", "delivery",
    # per-chunk partial sums change the delivery's float accumulation
    # order, exactly like delivery="invert" — a resume under a different
    # chunking silently continues a different-accumulation-order trajectory
    "edge_chunks",
    # the fault schedule is part of the trajectory: resuming a faulted run
    # under a different --fail-fraction (or a plain run under a fault plan)
    # would splice two different worlds. Stored as a stable content digest
    # — the schedule itself can be large (trajectory_meta normalizes it)
    "fault_schedule",
    # the repair policy rewrites the adjacency at strike rounds, so it is
    # as trajectory-defining as the schedule itself: resuming a rewire run
    # under prune (or off) would replay different topologies from the same
    # checkpoint — refused, like any other trajectory-field mismatch
    "repair",
    # the decentralized-learning knobs: payload width changes every state
    # shape, the workload swaps the round function entirely, and the SGP /
    # acceleration hyperparameters steer each round's arithmetic — a
    # resume under any other value continues a different trajectory
    "payload_dim", "workload", "accel", "accel_lambda", "lr",
    "local_steps", "sgp_samples", "loss_tol",
    # the execution clock: a poisson run activates a different sender
    # subset every round than a sync run, and the rate/grouping select
    # which subset — resuming under any other clock splices trajectories
    "clock", "activation_rate", "groups",
    # the topology-schedule event plan (events/) rewrites the adjacency
    # mid-run exactly like repair: resume replays the remaining events
    # bitwise only against the same plan. Stored as a content digest —
    # explicit edge lists can be large (trajectory_meta normalizes it)
    "event_plan",
    # kernel/wire execution shape: rounds_per_kernel changes the chunk
    # super-step granularity (trace rows, counter folding, round-limit
    # overshoot inside a super-step) and payload_wire changes the
    # sharded exchange's float values — resuming under a different K or
    # wire format splices trajectories and is refused. exchange_overlap
    # is deliberately NOT here: it moves identical bytes in an identical
    # order, bitwise-equal to the start-all-then-wait transport.
    "rounds_per_kernel", "payload_wire",
    # seeded value-fault injections (events/plan.py) corrupt protocol
    # state at their rounds: resuming under a different fault plan would
    # splice two different corruption histories. Stored as the plan's
    # dedicated value-fault digest ("none" for injection-free runs), so
    # it pins independently of the topology-event portion. The sentinel
    # mode itself is deliberately NOT a trajectory field: like telemetry
    # it only observes (quarantines it *performs* are recorded per-
    # checkpoint in the "quarantines" metadata and replayed from there).
    "value_faults",
)


# Fields a pre-upgrade checkpoint lacks but whose value is nevertheless
# known: the knob did not exist when the checkpoint was written, so the run
# necessarily used the default. Distinct from genuinely-unknowable absent
# fields which resume validation must wildcard — pre-upgrade eps/tol, and
# edge_chunks, whose CLI knob predates its trajectory-field status: a
# missing-key checkpoint may have run with ANY chunking, so pinning it
# would falsely reject the matching resume and silently accept chunking=1.
LEGACY_FIELD_DEFAULTS = {"fanout": "one", "delivery": "scatter",
                         # pre-repair checkpoints necessarily ran with the
                         # only behavior that existed: no repair
                         "repair": "off",
                         # pre-learn checkpoints are the scalar averaging
                         # protocol: one payload column, no workload, no
                         # acceleration (the SGP/accel hyperparameters are
                         # moot under those and wildcard like eps/tol)
                         "payload_dim": 1, "workload": "avg",
                         "accel": "off",
                         # pre-async checkpoints ran the only clock that
                         # existed: synchronous, ungrouped (the rate is
                         # moot under sync but its default is pinned so
                         # resumes never wildcard a poisson rate onto it)
                         "clock": "sync", "activation_rate": 1.0,
                         "groups": 1,
                         # pre-events checkpoints ran on a static (or
                         # repair-only) adjacency: no event plan
                         "event_plan": "none",
                         # pre-megakernel checkpoints ran one round per
                         # kernel on the uncompressed f32 wire — the only
                         # behavior that existed
                         "rounds_per_kernel": 1, "payload_wire": "f32",
                         # pre-sentinel checkpoints never injected value
                         # faults (the knob did not exist)
                         "value_faults": "none"}

# Sentinel written for alert_quorum=None (the all-nodes stop rule). None
# cannot be stored raw: resume validation could not tell "all-nodes run"
# from "field absent, value unknowable", and splicing a quorum run onto an
# all-nodes run must be an error (see field_matches).
_QUORUM_ALL = "all"


def field_matches(meta: dict, field: str, value) -> bool:
    """Resume validation for one trajectory field.

    Missing fields wildcard (pre-upgrade checkpoint, value unknowable) —
    except those in :data:`LEGACY_FIELD_DEFAULTS`, where missing means
    "the default": resuming an old single-target/scatter checkpoint under
    ``--fanout all`` or ``--delivery invert`` must be a mismatch, not a
    silent splice of two different trajectories.

    ``alert_quorum`` is special: ``None`` is a *real value* there (the
    all-nodes stop rule), so a stored null — written by checkpoints that
    predate the :data:`_QUORUM_ALL` sentinel — means "all nodes", not
    "unknowable"; only a checkpoint whose metadata lacks the key entirely
    wildcards.
    """
    stored = stored_value(meta, field)
    if stored is None:
        return True
    if field == "alert_quorum" and value is None:
        value = _QUORUM_ALL
    return stored == value


def stored_value(meta: dict, field: str):
    """The normalized stored value resume validation compares against,
    or ``None`` when the field wildcards (genuinely unknowable).

    Shared by :func:`field_matches` and the CLI's mismatch message so the
    reported value is always the one the comparison used — a raw ``meta``
    read would print ``None`` for a legacy pinned default or for
    alert_quorum's null encoding, both of which read as
    "unknowable/wildcard" to a user who just learned the wildcarding rules.
    """
    if field == "alert_quorum":
        if field not in meta:
            return None  # pre-quorum checkpoint
        return _QUORUM_ALL if meta[field] is None else meta[field]
    stored = meta.get(field)
    if stored is None:
        stored = LEGACY_FIELD_DEFAULTS.get(field)
    return stored


def trajectory_meta(cfg) -> dict:
    """JSON-able dict of every trajectory-affecting config field.

    The single source of truth for both sides of resume validation: save()
    embeds it in checkpoint metadata, the CLI compares it against the
    resuming run's config — no hand-duplicated field mapping to drift.
    """
    meta = {f: getattr(cfg, f, None) for f in TRAJECTORY_FIELDS}
    if meta["alert_quorum"] is None:
        meta["alert_quorum"] = _QUORUM_ALL
    if meta.get("dtype") is not None:
        # jnp.float32 the class is not JSON-able; its dtype name is
        meta["dtype"] = np.dtype(meta["dtype"]).name
    # the schedule is stored as its content digest: stable across
    # equivalent spellings (legacy fault_plan dict vs FaultSchedule), small,
    # and "none" for the no-fault run so plain resumes keep matching
    from gossipprotocol_tpu.utils import faults

    meta["fault_schedule"] = faults.as_schedule(
        getattr(cfg, "fault_schedule", None), getattr(cfg, "fault_plan", None)
    ).digest()
    # likewise the event plan: its digest, "none" for plan-free runs
    from gossipprotocol_tpu.events import plan as events_plan

    plan = events_plan.as_plan(getattr(cfg, "event_plan", None))
    meta["event_plan"] = plan.digest()
    # the value-fault portion pins separately (see TRAJECTORY_FIELDS)
    meta["value_faults"] = plan.value_fault_digest()
    return meta


def topology_fingerprint(topo) -> str:
    """Cheap content hash of the adjacency itself.

    Comparing builder *inputs* on resume (kind, node count) misses knobs
    like --avg-degree/--attach that yield a different graph from the same
    kind and size; hashing the CSR catches every such mismatch. crc32 runs
    at GB/s, so this costs well under a second even at 10M nodes.
    """
    import zlib

    if topo.implicit_full:
        return f"full/{topo.num_nodes}"
    streamed = getattr(topo, "fingerprint", None)
    if streamed is not None:
        # a streamed ShardedTopology crc's its slices in order — same
        # byte stream, same fingerprint as the materialized CSR
        return streamed()
    crc = zlib.crc32(topo.indices.tobytes())
    crc = zlib.crc32(topo.offsets.tobytes(), crc)
    return f"{topo.num_nodes}/{topo.num_directed_edges}/{crc:08x}"


def fetch_host(state):
    """Host copy of a (possibly multi-process) state pytree.

    Under ``jax.distributed`` the mesh spans processes, so state shards
    are not all addressable locally and plain ``device_get`` raises; every
    process then reassembles the full arrays collectively (the DCN
    analogue of fetching from remote actors).
    """
    if all(
        getattr(x, "is_fully_addressable", True) for x in jax.tree.leaves(state)
    ):
        return jax.device_get(state)
    from jax.experimental import multihost_utils

    return jax.device_get(multihost_utils.process_allgather(state, tiled=True))


def save(
    directory: str, state, cfg, topo_kind: str, adjacency: str | None = None,
    extra_meta: dict | None = None
) -> str:
    """Write ``state`` to ``directory/ckpt_round{R}.npz``; returns the path.

    ``adjacency``: :func:`topology_fingerprint` of the run's graph (the
    driver computes it once per run, not per checkpoint).

    ``extra_meta``: additional JSON-able metadata (the drive loop records
    sentinel quarantines here — dynamic kills a resume replay could not
    re-derive from the declarative plan).
    """
    os.makedirs(directory, exist_ok=True)
    # fetch_host is a collective under jax.distributed — every process must
    # call it — but only one process may publish: concurrent writers would
    # race on the tmp path and could publish a truncated/interleaved zip
    host = fetch_host(state)
    arrays = {f: np.asarray(v) for f, v in zip(type(state)._fields, host)}
    meta = {
        "state_type": type(state).__name__,
        "round": int(arrays["round"]),
        "topology": topo_kind,
        "adjacency": adjacency,
        "saved_at": time.time(),
        **(extra_meta or {}),
        **trajectory_meta(cfg),
    }
    path = os.path.join(directory, f"ckpt_round{meta['round']:09d}.npz")
    if jax.process_index() != 0:
        return path
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, __meta__=json.dumps(meta), **arrays)
    os.replace(tmp, path)
    _sweep_stale_tmps(directory, meta["round"])
    return path


def _sweep_stale_tmps(directory: str, published_round: int) -> None:
    """Remove tmp debris left by crashed saves.

    A crash between ``savez`` and ``os.replace`` leaves a
    ``ckpt_round*.npz.tmp.npz`` behind; once a checkpoint at the same or
    a later round publishes, that tmp can never be promoted and would
    otherwise accumulate forever. Saves are single-writer (process 0
    only, see ``save``), so a tmp at ``round <= published_round`` is
    guaranteed dead — tmps for *later* rounds (a crashed save from a
    run that got further than this one before restarting) are left
    alone until a publish catches up with them.
    """
    prefix, suffix = "ckpt_round", ".npz.tmp.npz"
    for f in os.listdir(directory):
        if not (f.startswith(prefix) and f.endswith(suffix)):
            continue
        try:
            r = int(f[len(prefix):-len(suffix)])
        except ValueError:
            continue
        if r <= published_round:
            try:
                os.unlink(os.path.join(directory, f))
            except OSError:
                pass


def load(path: str) -> Tuple[object, dict]:
    """Load a checkpoint; returns (state pytree, metadata dict)."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        cls = _STATE_TYPES[meta["state_type"]]
        import jax.numpy as jnp

        fields = [jnp.asarray(z[f]) for f in cls._fields]
    return cls(*fields), meta


def candidates(directory: str) -> list:
    """Published checkpoint paths in ``directory``, newest first.

    The resume fallback chain walks this list: a *published* checkpoint
    can still be unreadable (bitrot, or a torn write on a filesystem
    where rename is not atomic), so callers probe each entry with
    :func:`peek_meta`/:func:`load` and fall back to the next on failure.

    ``.tmp.npz`` files are in-flight writes (``save`` publishes via
    ``os.replace``): a crash mid-save can leave a truncated one behind,
    and it must never shadow the last *published* checkpoint — published
    files are atomic-renamed and therefore normally complete.
    """
    if not os.path.isdir(directory):
        return []
    cands = sorted(
        (f for f in os.listdir(directory)
         if f.startswith("ckpt_round") and f.endswith(".npz")
         and not f.endswith(".tmp.npz")),
        reverse=True,
    )
    return [os.path.join(directory, f) for f in cands]


def latest(directory: str) -> str | None:
    """Path of the newest checkpoint in ``directory``, or None.

    (Head of :func:`candidates` — kept as the single-checkpoint entry
    point for callers that do not want the fallback chain.)
    """
    cands = candidates(directory)
    return cands[0] if cands else None


def latest_resumable(directory: str) -> Tuple[str, int] | None:
    """``(path, round)`` of the newest *readable* checkpoint, or None.

    Stronger than :func:`latest`: each candidate's metadata is actually
    read, so a published-but-corrupt head entry falls through to the
    next instead of being promised to a caller. The serve/ supervisor
    uses this during crash recovery — a resume it announces in the
    journal must be one the worker can deliver.
    """
    for path in candidates(directory):
        try:
            meta = peek_meta(path)
            return path, int(meta.get("round", -1))
        except Exception:
            continue
    return None


def peek_meta(path: str) -> dict:
    """Metadata only, without materializing the state arrays.

    npz members load lazily, so this reads one small zip entry — cheap
    even for a 10M-node checkpoint (whose arrays are ~hundreds of MB).
    Used by recovery-target selection, which must compare the *rounds* of
    candidate checkpoints before committing to one.
    """
    with np.load(path, allow_pickle=False) as z:
        return json.loads(str(z["__meta__"]))
