"""Structured metrics / observability (SURVEY.md §5.5).

The reference's entire observability surface is three ``printfn`` lines —
two start banners and the one metric (``Program.fs:55,198,204``). Here
every chunk of rounds emits a structured record (round, #converged, ratio
spread), streamable to a JSONL file for the BASELINE-style curves, and the
final metric is printed in the reference's exact format so downstream
tooling that scraped the F# output keeps working.
"""

from __future__ import annotations

import json
import sys
from typing import IO, Optional


class JsonlMetricsWriter:
    """Append one JSON object per metrics record to a file (or stream)."""

    def __init__(self, path_or_stream, mode: str = "w"):
        if isinstance(path_or_stream, str):
            # "w" by default: rerunning with the same --metrics-out must not
            # interleave records from unrelated runs in one JSONL file. A
            # resume of the same logical run passes mode="a" so the pre-crash
            # records survive and the file covers the whole trajectory.
            self._fh: IO = open(path_or_stream, mode, buffering=1)
            self._owns = True
        else:
            self._fh = path_or_stream
            self._owns = False

    def __call__(self, record: dict) -> None:
        self._fh.write(json.dumps(record) + "\n")

    def close(self) -> None:
        if self._owns:
            self._fh.close()


def print_start_banner(algorithm: str, stream: Optional[IO] = None) -> None:
    """Reference start banners: "Gossip Starts" / "Push Sum Starts"
    (``Program.fs:198,204``)."""
    stream = stream or sys.stdout
    print("Gossip Starts" if algorithm == "gossip" else "Push Sum Starts", file=stream)


def print_convergence_time(wall_ms: float, stream: Optional[IO] = None) -> None:
    """The reference's single output metric, format-compatible with
    ``printfn "Convergence Time: %f ms"`` (``Program.fs:55``)."""
    stream = stream or sys.stdout
    print(f"Convergence Time: {wall_ms:f} ms", file=stream)
