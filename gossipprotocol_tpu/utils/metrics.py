"""Structured metrics / observability (SURVEY.md §5.5).

The reference's entire observability surface is three ``printfn`` lines —
two start banners and the one metric (``Program.fs:55,198,204``). Here
every chunk of rounds emits a structured record (round, #converged, ratio
spread), streamable to a JSONL file for the BASELINE-style curves, and the
final metric is printed in the reference's exact format so downstream
tooling that scraped the F# output keeps working.

Record schema: version :data:`SCHEMA_VERSION` (currently 1), described by
:func:`schema`. A record without a ``"v"`` field IS version 1 — stamping
is opt-in (the telemetry path turns it on) because a pre-telemetry run's
metrics file must stay byte-identical when nothing else changed. Readers
(``obs/report.py``) must accept absent-``v`` records and refuse higher
major versions loudly.
"""

from __future__ import annotations

import json
import sys
from typing import IO, Optional

# Single schema version for every telemetry record family (per-chunk
# metrics records, events.jsonl lines, run.json manifests): they are read
# together by `python -m gossipprotocol_tpu report` and version together.
SCHEMA_VERSION = 1


def schema() -> dict:
    """Machine-readable description of the version-1 record families.

    Not a validator — a contract note for downstream consumers and the
    ``report`` subcommand's version gate.
    """
    return {
        "v": SCHEMA_VERSION,
        "chunk_record": {
            "round": "int — cumulative round count at chunk end",
            "converged": "int — alive nodes whose predicate holds",
            "alive": "int — alive nodes",
            "ratio_min/ratio_max": "float — push-sum estimate spread",
            "w_underflow": "int — alive rows with w == 0 (dry-spell wall)",
            "spreading": "int — gossip rows still able to deliver a hit",
            "sent/delivered/dropped":
                "int — message counters (telemetry runs only)",
            "mass_drift_ulps/w_drift_ulps":
                "float — |Σ − baseline| in baseline ULPs (telemetry runs)",
            "stalled": "bool — gossip liveness failure, run ended early",
        },
        "event_record": {
            "event": "str — 'repair' | 'resumed' | 'restarted_from_scratch'",
        },
    }


class JsonlMetricsWriter:
    """Append one JSON object per metrics record to a file (or stream).

    Context-manager use is the exception-safe form — the file is flushed
    and closed on any exit path::

        with JsonlMetricsWriter(path) as w:
            w({"round": 0})

    Resume contract: a resume (or recovery re-exec) of the same logical
    run MUST pass ``mode="a"`` so the pre-crash records survive and one
    file covers the whole trajectory; semantics are then at-least-once
    (chunks after the last checkpoint replay and re-emit), with a marker
    record separating the attempts — consumers dedup on ``round`` after
    the marker. The ``"w"`` default is for fresh runs: rerunning with the
    same ``--metrics-out`` must not interleave unrelated runs in one file.

    ``stamp_version=True`` adds ``"v": SCHEMA_VERSION`` to every record;
    off by default so a telemetry-free run's output is byte-identical to
    pre-telemetry builds (absent ``"v"`` means version 1 by definition).
    """

    def __init__(self, path_or_stream, mode: str = "w",
                 stamp_version: bool = False):
        if isinstance(path_or_stream, str):
            self._fh: IO = open(path_or_stream, mode, buffering=1)
            self._owns = True
        else:
            self._fh = path_or_stream
            self._owns = False
        self._stamp = bool(stamp_version)
        self._closed = False

    def __call__(self, record: dict) -> None:
        if self._stamp and "v" not in record:
            record = {"v": SCHEMA_VERSION, **record}
        self._fh.write(json.dumps(record) + "\n")

    def close(self) -> None:
        """Flush and (for owned files) close; idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._owns:
            self._fh.close()
        else:
            # borrowed stream: the caller owns the lifetime, but records
            # must still be durable once the writer is done with it
            try:
                self._fh.flush()
            except (OSError, ValueError):
                pass

    def __enter__(self) -> "JsonlMetricsWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def print_start_banner(algorithm: str, stream: Optional[IO] = None) -> None:
    """Reference start banners: "Gossip Starts" / "Push Sum Starts"
    (``Program.fs:198,204``)."""
    stream = stream or sys.stdout
    print("Gossip Starts" if algorithm == "gossip" else "Push Sum Starts", file=stream)


def print_convergence_time(wall_ms: float, stream: Optional[IO] = None) -> None:
    """The reference's single output metric, format-compatible with
    ``printfn "Convergence Time: %f ms"`` (``Program.fs:55``)."""
    stream = stream or sys.stdout
    print(f"Convergence Time: {wall_ms:f} ms", file=stream)
