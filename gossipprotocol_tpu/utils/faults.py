"""Fault injection (SURVEY.md §5.3).

The reference has no failure handling — an actor crash would hang the
supervisor forever. Here failures are a first-class *simulated* capability
(gossip's robustness under node loss is the algorithm's whole point): a
fault plan maps a round number to the node ids that die at that round. The
driver applies the plan between chunks; dead nodes neither send nor
receive, and the supervisor's predicate ignores them.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def random_fault_plan(
    num_nodes: int,
    fraction: float,
    at_round: int,
    seed: int = 0,
) -> Dict[int, np.ndarray]:
    """Kill a uniform-random ``fraction`` of nodes at ``at_round``."""
    if not 0.0 <= fraction < 1.0:
        raise ValueError("fraction must be in [0, 1)")
    rng = np.random.default_rng(seed)
    k = int(round(num_nodes * fraction))
    ids = rng.choice(num_nodes, size=k, replace=False)
    return {int(at_round): np.sort(ids)}


def kill_disconnected(topo, alive: np.ndarray) -> np.ndarray:
    """Keep only the largest alive connected component; everything else
    is marked dead.

    Majority-partition semantics, applied both at birth and after every
    fault strike. Two hazards force this, and both would otherwise hang
    any sound convergence predicate forever — the very supervisor hang
    the reference would exhibit (SURVEY.md §5.3):

    * **Stranding** — a fault can cut a survivor off from every alive
      neighbor (at the 10M Erdős–Rényi north star, killing 1 % of nodes
      strands an expected ~270 degree-1 survivors); its state freezes and
      the predicate waits on it forever.
    * **Minority components** — sparse random graphs are born with small
      components (ER(8)@10M: a handful of isolated pairs/triples), and a
      fault can split more off. Push-sum provably averages *within* a
      component, so a minority component converges to its own mean, never
      the global one; gossip's rumor can never cross to it at all.

    Treating unreachable-from-the-majority as failed is the standard
    failure-detector / partition-tolerance reading: the majority side
    continues, the minority stops counting. If the largest component has
    fewer than 2 nodes, everyone is marked dead (a single node cannot run
    a message-passing protocol).

    Host-side scipy over the CSR (runs at build time and at fault rounds,
    never in the round loop; ~seconds at 10M nodes / 80M edges).
    """
    alive = np.asarray(alive, dtype=bool).copy()
    if topo.implicit_full:
        # any two alive nodes are neighbors: one component by definition
        if alive.sum() < 2:
            alive[:] = False
        return alive
    from scipy import sparse
    from scipy.sparse import csgraph

    n = topo.num_nodes
    row = np.repeat(np.arange(n, dtype=np.int64), np.diff(topo.offsets))
    col = np.asarray(topo.indices, dtype=np.int64)
    live = alive[row] & alive[col]
    g = sparse.csr_matrix(
        (np.ones(int(live.sum()), dtype=np.int8), (row[live], col[live])),
        shape=(n, n),
    )
    _, labels = csgraph.connected_components(g, directed=False)
    sizes = np.bincount(labels[alive]) if alive.any() else np.zeros(1, int)
    if sizes.size == 0 or sizes.max() < 2:
        alive[:] = False
        return alive
    return alive & (labels == int(sizes.argmax()))
