"""Fault injection (SURVEY.md §5.3): the fault-schedule engine.

The reference has no failure handling — an actor crash would hang the
supervisor forever. Here failures are a first-class *simulated* capability
(gossip's robustness under churn and loss is the algorithm's whole point,
arXiv:1811.10792 §5 / arXiv:1906.04585 §4). A :class:`FaultSchedule` is a
declarative timeline of three event kinds:

* ``kill``   — node ids die at a round: they neither send nor receive, the
  supervisor's predicate ignores them, and their ``(s, w)`` mass strands.
* ``revive`` — node ids rejoin at a round **with fresh-born state** (a
  crashed process restarting from its initial value, not a resurrected
  one): gossip counts reset to 0, push-sum ``(s, w)`` to the init values.
  After every strike batch :func:`kill_disconnected` re-runs, so a revived
  node only counts once it is reattached to the majority component.
* ``loss``   — link-level message loss windows ``[start, stop)`` with a
  per-message Bernoulli drop probability. Drops are **mass-conserving**
  for push-sum: a dropped send returns its ``(s, w)`` share to the sender
  rather than evaporating, so ``Σs/Σw == mean`` survives and
  ``estimate_error`` stays meaningful. Drop draws are counter-based on the
  run PRNG (keyed on round + sender/edge global ids), so trajectories are
  reproducible and sharding-invariant.

Kills and revives are host events: the driver stops each jitted chunk
exactly at the next event round and applies the strike between chunks —
since the unified topology-schedule engine
(:mod:`gossipprotocol_tpu.events`) subsumed the inline fault machinery,
that pipeline is :class:`gossipprotocol_tpu.events.engine.HostEvents`,
which folds strikes together with edge churn and repair; this module
stays the declarative schedule model and the partition-rule primitives.
Loss windows are *device* events: the round kernels compute the active
drop probability from ``state.round`` against the (static) window table,
so chunks never need to stop at window boundaries.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, Mapping, Optional, Tuple

import numpy as np


def random_fault_plan(
    num_nodes: int,
    fraction: float,
    at_round: int,
    seed: int = 0,
) -> Dict[int, np.ndarray]:
    """Kill a uniform-random ``fraction`` of nodes at ``at_round``."""
    if not 0.0 <= fraction < 1.0:
        raise ValueError("fraction must be in [0, 1)")
    rng = np.random.default_rng(seed)
    k = int(round(num_nodes * fraction))
    ids = rng.choice(num_nodes, size=k, replace=False)
    return {int(at_round): np.sort(ids)}


@dataclasses.dataclass(frozen=True)
class LossWindow:
    """Per-message Bernoulli loss over rounds ``[start, stop)``."""

    start: int
    stop: int     # exclusive
    prob: float   # in [0, 1)


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    """Declarative timeline of kill / revive / link-loss events.

    ``kills``/``revives`` map a round number to the (sorted, unique) node
    ids struck at that round. Treated as immutable after construction.
    """

    kills: Mapping[int, np.ndarray] = dataclasses.field(default_factory=dict)
    revives: Mapping[int, np.ndarray] = dataclasses.field(default_factory=dict)
    loss: Tuple[LossWindow, ...] = ()

    # ---- queries -------------------------------------------------------

    @property
    def has_strikes(self) -> bool:
        """Any aliveness-changing event (kill or revive)? These disable
        the engine's static liveness fast paths; loss windows alone do
        not (drops change delivery, never aliveness)."""
        return bool(self.kills) or bool(self.revives)

    @property
    def has_loss(self) -> bool:
        return bool(self.loss)

    def __bool__(self) -> bool:
        return self.has_strikes or self.has_loss

    def static_loss_windows(self) -> Tuple[Tuple[int, int, float], ...]:
        """Hashable ``(start, stop, prob)`` tuple for jit static args."""
        return tuple((w.start, w.stop, float(w.prob)) for w in self.loss)

    # ---- validation ----------------------------------------------------

    def validate(self, num_nodes: Optional[int] = None) -> "FaultSchedule":
        """Structural validation; raises ValueError with the bad entry
        named. Returns self so call sites can chain."""
        for name, events in (("kill", self.kills), ("revive", self.revives)):
            for r, ids in events.items():
                if int(r) < 0:
                    raise ValueError(f"{name} round {r} is negative")
                a = np.asarray(ids)
                if a.size and (a < 0).any():
                    raise ValueError(f"{name}@{r}: negative node id")
                if num_nodes is not None and a.size and (a >= num_nodes).any():
                    raise ValueError(
                        f"{name}@{r}: node id {int(a.max())} out of range "
                        f"for {num_nodes} nodes"
                    )
        for r in self.kills:
            if r in self.revives:
                both = np.intersect1d(
                    np.asarray(self.kills[r]), np.asarray(self.revives[r])
                )
                if both.size:
                    raise ValueError(
                        f"round {r}: node(s) {both.tolist()} appear in both "
                        "kill and revive — same-round kill+revive of one "
                        "node is order-ambiguous; schedule them one round "
                        "apart"
                    )
        for w in self.loss:
            if not 0.0 <= w.prob < 1.0:
                raise ValueError(
                    f"loss window [{w.start}, {w.stop}): prob {w.prob} "
                    "must be in [0, 1) — prob 1.0 drops every message "
                    "forever, which no protocol can survive"
                )
            if w.start < 0 or w.stop <= w.start:
                raise ValueError(
                    f"loss window [{w.start}, {w.stop}) is empty or "
                    "negative (stop is exclusive and must exceed start)"
                )
        return self

    # ---- construction --------------------------------------------------

    @classmethod
    def from_events(
        cls,
        kills: Optional[Mapping[int, object]] = None,
        revives: Optional[Mapping[int, object]] = None,
        loss: Tuple[LossWindow, ...] = (),
    ) -> "FaultSchedule":
        norm = lambda ev: {  # noqa: E731
            int(r): np.unique(np.asarray(ids, dtype=np.int64))
            for r, ids in (ev or {}).items()
        }
        return cls(kills=norm(kills), revives=norm(revives), loss=tuple(loss))

    @classmethod
    def from_json(
        cls, obj, num_nodes: Optional[int] = None, seed: int = 0
    ) -> "FaultSchedule":
        """Parse the ``--fault-plan`` JSON document.

        Format (every key optional)::

            {
              "kill":   [{"round": 10, "ids": [1, 2]},
                         {"round": 12, "fraction": 0.1, "seed": 7}],
              "revive": [{"round": 30, "ids": [1, 2]}],
              "loss":   [{"start": 5, "stop": 25, "prob": 0.2}]
            }

        ``fraction`` kills draw uniform-random ids (like
        ``--fail-fraction``); their ``seed`` defaults to the run seed so
        the schedule stays reproducible without repeating it.
        """
        if isinstance(obj, str):
            with open(obj) as f:
                obj = json.load(f)
        if not isinstance(obj, dict):
            raise ValueError("fault plan must be a JSON object")
        unknown = set(obj) - {"kill", "revive", "loss"}
        if unknown:
            raise ValueError(
                f"fault plan: unknown key(s) {sorted(unknown)} "
                "(valid: kill, revive, loss)"
            )
        kills: Dict[int, np.ndarray] = {}
        for ev in obj.get("kill", ()):
            r = int(ev["round"])
            if "ids" in ev:
                ids = np.asarray(ev["ids"], dtype=np.int64)
            elif "fraction" in ev:
                if num_nodes is None:
                    raise ValueError(
                        "fraction kill events need the node count"
                    )
                ids = random_fault_plan(
                    num_nodes, float(ev["fraction"]), r,
                    seed=int(ev.get("seed", seed)),
                )[r]
            else:
                raise ValueError(f"kill@{r}: needs 'ids' or 'fraction'")
            kills[r] = np.union1d(kills.get(r, np.empty(0, np.int64)), ids)
        revives: Dict[int, np.ndarray] = {}
        for ev in obj.get("revive", ()):
            r = int(ev["round"])
            ids = np.asarray(ev["ids"], dtype=np.int64)
            revives[r] = np.union1d(
                revives.get(r, np.empty(0, np.int64)), ids
            )
        loss = tuple(
            LossWindow(int(w["start"]), int(w["stop"]), float(w["prob"]))
            for w in obj.get("loss", ())
        )
        return cls.from_events(kills, revives, loss).validate(num_nodes)

    # ---- identity ------------------------------------------------------

    def digest(self) -> str:
        """Stable content hash, for checkpoint trajectory metadata.

        The schedule shapes the trajectory exactly like the PRNG seed
        does, so resume validation must compare it; the digest keeps the
        metadata record small and order-canonical. ``"none"`` for the
        empty schedule so a no-fault resume of a no-fault checkpoint
        matches without wildcarding."""
        if not self:
            return "none"
        doc = {
            "kill": {str(r): np.asarray(v).tolist()
                     for r, v in sorted(self.kills.items())},
            "revive": {str(r): np.asarray(v).tolist()
                       for r, v in sorted(self.revives.items())},
            "loss": [[w.start, w.stop, w.prob] for w in self.loss],
        }
        blob = json.dumps(doc, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def as_schedule(
    fault_schedule: Optional[FaultSchedule],
    fault_plan: Optional[Mapping[int, object]] = None,
) -> FaultSchedule:
    """Normalize RunConfig's fault fields into one FaultSchedule.

    ``fault_plan`` is the legacy one-shot kill mapping ``{round: ids}``;
    it merges into the schedule's kills so every pre-schedule call site
    (tests, notebooks) keeps working unchanged.
    """
    sched = fault_schedule or FaultSchedule()
    if not fault_plan:
        return sched
    kills = {int(r): np.asarray(v) for r, v in sched.kills.items()}
    for r, ids in fault_plan.items():
        r = int(r)
        ids = np.asarray(ids, dtype=np.int64)
        kills[r] = np.union1d(kills.get(r, np.empty(0, np.int64)), ids)
    return FaultSchedule.from_events(kills, sched.revives, sched.loss)


def merge_schedules(*schedules: Optional[FaultSchedule],
                    ) -> Optional[FaultSchedule]:
    """Union several fault schedules (per-round id unions, loss windows
    concatenated in argument order).

    The CLI merges the legacy ``--fault-plan``/``--fail-*`` schedule with
    the fault keys an ``--event-plan`` document carries — both compile
    down to the same engine. Returns None when every input is empty, so
    plain runs keep the static fast paths.
    """
    live = [s for s in schedules if s]
    if not live:
        return None
    if len(live) == 1:
        return live[0]
    kills: Dict[int, np.ndarray] = {}
    revives: Dict[int, np.ndarray] = {}
    loss: list = []
    for s in live:
        for dst, src in ((kills, s.kills), (revives, s.revives)):
            for r, ids in src.items():
                r = int(r)
                dst[r] = np.union1d(
                    dst.get(r, np.empty(0, np.int64)),
                    np.asarray(ids, np.int64))
        loss.extend(s.loss)
    return FaultSchedule.from_events(kills, revives, tuple(loss))


def build_schedule(
    num_nodes: int,
    plan_file: Optional[str] = None,
    fail_fraction: float = 0.0,
    fail_round: int = 0,
    revive_round: Optional[int] = None,
    drop_prob: float = 0.0,
    drop_window: Optional[Tuple[int, int]] = None,
    seed: int = 0,
    max_rounds: int = 1_000_000,
) -> Optional[FaultSchedule]:
    """CLI sugar + optional JSON plan -> one validated FaultSchedule.

    Sugar renders to the same event model the JSON carries:
    ``--fail-fraction F --fail-round R`` is a fraction kill at R,
    ``--revive-round R2`` revives exactly those killed ids at R2, and
    ``--drop-prob P [--drop-window A B]`` is one loss window (the whole
    run when no window is given). Returns None when nothing is scheduled,
    so a plain run keeps the engine's static fast paths.
    """
    sched = (FaultSchedule.from_json(plan_file, num_nodes, seed=seed)
             if plan_file else FaultSchedule())
    kills = dict(sched.kills)
    revives = dict(sched.revives)
    loss = list(sched.loss)
    sugar_ids = None
    if fail_fraction > 0:
        plan = random_fault_plan(num_nodes, fail_fraction, fail_round,
                                 seed=seed)
        sugar_ids = plan[int(fail_round)]
        kills[int(fail_round)] = np.union1d(
            kills.get(int(fail_round), np.empty(0, np.int64)), sugar_ids
        )
    if revive_round is not None:
        if sugar_ids is None:
            raise ValueError(
                "--revive-round revives the --fail-fraction victims; it "
                "needs --fail-fraction > 0 (schedule explicit revives "
                "via --fault-plan)"
            )
        if revive_round <= fail_round:
            raise ValueError(
                f"--revive-round {revive_round} must come after "
                f"--fail-round {fail_round}"
            )
        revives[int(revive_round)] = np.union1d(
            revives.get(int(revive_round), np.empty(0, np.int64)), sugar_ids
        )
    if drop_window is not None and drop_prob <= 0:
        raise ValueError("--drop-window needs --drop-prob > 0")
    if drop_prob > 0:
        start, stop = drop_window if drop_window else (0, max_rounds)
        loss.append(LossWindow(int(start), int(stop), float(drop_prob)))
    out = FaultSchedule.from_events(kills, revives, tuple(loss))
    out.validate(num_nodes)
    return out if out else None


def kill_disconnected(topo, alive: np.ndarray) -> np.ndarray:
    """Keep only the largest alive connected component; everything else
    is marked dead.

    Majority-partition semantics, applied at birth and after every
    strike batch (kills AND revives — a revived node that is not
    reattached to the majority component must not start counting). Two
    hazards force this, and both would otherwise hang any sound
    convergence predicate forever — the very supervisor hang the
    reference would exhibit (SURVEY.md §5.3):

    * **Stranding** — a fault can cut a survivor off from every alive
      neighbor (at the 10M Erdős–Rényi north star, killing 1 % of nodes
      strands an expected ~270 degree-1 survivors); its state freezes and
      the predicate waits on it forever.
    * **Minority components** — sparse random graphs are born with small
      components (ER(8)@10M: a handful of isolated pairs/triples), and a
      fault can split more off. Push-sum provably averages *within* a
      component, so a minority component converges to its own mean, never
      the global one; gossip's rumor can never cross to it at all.

    Treating unreachable-from-the-majority as failed is the standard
    failure-detector / partition-tolerance reading: the majority side
    continues, the minority stops counting. If the largest component has
    fewer than 2 nodes, everyone is marked dead (a single node cannot run
    a message-passing protocol).

    Host-side scipy over the CSR (runs at build time and at fault rounds,
    never in the round loop; ~seconds at 10M nodes / 80M edges).
    """
    alive = np.asarray(alive, dtype=bool).copy()
    if topo.implicit_full:
        # any two alive nodes are neighbors: one component by definition
        if alive.sum() < 2:
            alive[:] = False
        return alive
    from scipy import sparse
    from scipy.sparse import csgraph

    n = topo.num_nodes
    row = np.repeat(np.arange(n, dtype=np.int64), np.diff(topo.offsets))
    col = np.asarray(topo.indices, dtype=np.int64)
    live = alive[row] & alive[col]
    g = sparse.csr_matrix(
        (np.ones(int(live.sum()), dtype=np.int8), (row[live], col[live])),
        shape=(n, n),
    )
    _, labels = csgraph.connected_components(g, directed=False)
    sizes = np.bincount(labels[alive]) if alive.any() else np.zeros(1, int)
    if sizes.size == 0 or sizes.max() < 2:
        alive[:] = False
        return alive
    return alive & (labels == int(sizes.argmax()))


def apply_partition_rule(topo, alive: np.ndarray,
                         repair_policy: str = "off") -> np.ndarray:
    """Policy-conditional majority-partition rule.

    ``off`` and ``prune`` run :func:`kill_disconnected` with today's
    victim set: the engine hands in the birth adjacency (``off``) or the
    pruned one (``prune`` — dropping dead endpoints never changes the
    component structure *among live nodes*, the rule masks dead
    endpoints itself, so the victims match ``off`` bitwise).  Stranded
    survivors still die.

    ``rewire`` is the policy under which survivors are supposed to stay
    in the computation: the engine hands in the *repaired* adjacency,
    where the deterministic splice has already re-attached every orphan,
    so the rule is normally a no-op.  It still runs as a safety net for
    the rare fragment the pairing closed on itself (two stubs of one
    detached island pairing with each other) — such an island would
    otherwise hang a sound global predicate forever, exactly the hazard
    documented on :func:`kill_disconnected`.
    """
    from gossipprotocol_tpu.topology import repair as repair_mod

    repair_mod.validate_policy(repair_policy)
    return kill_disconnected(topo, alive)
