"""Fault injection (SURVEY.md §5.3).

The reference has no failure handling — an actor crash would hang the
supervisor forever. Here failures are a first-class *simulated* capability
(gossip's robustness under node loss is the algorithm's whole point): a
fault plan maps a round number to the node ids that die at that round. The
driver applies the plan between chunks; dead nodes neither send nor
receive, and the supervisor's predicate ignores them.
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np


def random_fault_plan(
    num_nodes: int,
    fraction: float,
    at_round: int,
    seed: int = 0,
) -> Dict[int, np.ndarray]:
    """Kill a uniform-random ``fraction`` of nodes at ``at_round``."""
    if not 0.0 <= fraction < 1.0:
        raise ValueError("fraction must be in [0, 1)")
    rng = np.random.default_rng(seed)
    k = int(round(num_nodes * fraction))
    ids = rng.choice(num_nodes, size=k, replace=False)
    return {int(at_round): np.sort(ids)}


def merge_plans(*plans: Dict[int, Sequence[int]]) -> Dict[int, np.ndarray]:
    out: Dict[int, np.ndarray] = {}
    for plan in plans:
        for r, ids in plan.items():
            prev = out.get(int(r), np.empty(0, dtype=np.int64))
            out[int(r)] = np.unique(np.concatenate([prev, np.asarray(ids, np.int64)]))
    return out
