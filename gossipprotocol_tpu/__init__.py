"""gossipprotocol_tpu — a TPU-native gossip / push-sum convergence framework.

A from-scratch, bulk-synchronous reimagining of the capabilities of the
reference actor-model simulator (sharwarimarathe/GossipProtocol,
``Project2/Program.fs``): N network nodes run either the **gossip**
rumor-spreading protocol or the **push-sum** distributed-averaging protocol
over a pluggable topology until global convergence, and the framework reports
wall-clock time to convergence.

Instead of one Akka actor per node exchanging asynchronous messages
(``Program.fs:36,65-137``), node state lives in dense JAX arrays sharded over
a TPU device mesh; one *round* advances every node simultaneously via a
random-neighbor gather + scatter-add (``jax.ops.segment_sum``), driven by
``lax.while_loop`` with the convergence supervisor's predicate as the loop
condition (``Program.fs:41-63`` → a ``psum``-reduced streak test).

Layer map (mirrors SURVEY.md §1):

=====  ==============================  ==============================
Layer  Reference (F#/Akka)             This framework (JAX/TPU)
=====  ==============================  ==============================
L5     CLI argv parse                  :mod:`gossipprotocol_tpu.cli`
L4     topology wiring + seeding       :mod:`gossipprotocol_tpu.topology`
L3     per-actor protocol handlers     :mod:`gossipprotocol_tpu.protocols`
L2     scheduler actor (supervisor)    :mod:`gossipprotocol_tpu.engine`
L1     Akka mailboxes                  :mod:`gossipprotocol_tpu.parallel`
=====  ==============================  ==============================
"""

from gossipprotocol_tpu.version import __version__

from gossipprotocol_tpu.topology import (
    Topology,
    build_topology,
    available_topologies,
)
from gossipprotocol_tpu.protocols import (
    GossipState,
    PushSumState,
    gossip_init,
    pushsum_init,
    make_gossip_round,
    make_pushsum_round,
)
from gossipprotocol_tpu.engine import (
    RunConfig,
    RunResult,
    run_simulation,
)

__all__ = [
    "__version__",
    "Topology",
    "build_topology",
    "available_topologies",
    "GossipState",
    "PushSumState",
    "gossip_init",
    "pushsum_init",
    "make_gossip_round",
    "make_pushsum_round",
    "RunConfig",
    "RunResult",
    "run_simulation",
]
