"""Command-line interface (reference L5, ``Program.fs:30-37``).

The reference's positional surface is preserved exactly:

    python -m gossipprotocol_tpu <num_nodes> <topology> <algorithm>

with ``topology`` ∈ {line, full, 3D, imp3D, erdos_renyi, power_law,
small_world} and
``algorithm`` ∈ {gossip, push-sum} (hyphenated, matching the reference's
match arm ``Program.fs:196-205``; "push_sum"/"pushsum" accepted as
aliases). Output is format-compatible: the start banner
("Gossip Starts" / "Push Sum Starts") and the one metric
``Convergence Time: %f ms`` (``Program.fs:55``).

Beyond the reference (north-star flags, BASELINE.json): ``--backend``,
``--seed``, ``--threshold``, ``--eps``, ``--streak``, ``--max-rounds``,
``--semantics``, ``--predicate/--tol`` (sound convergence),
``--fanout`` (diffusion push-sum), ``--delivery`` (scatter vs gather
inversion), ``--metrics-out``, ``--checkpoint-dir``, ``--resume``,
``--auto-resume`` (elastic recovery), ``--compile-cache``,
``--fail-fraction/--fail-round``, ``--revive-round`` (churn),
``--drop-prob/--drop-window`` (mass-conserving message loss),
``--fault-plan`` (declarative JSON fault schedule),
``--event-plan``/``--churn`` (unified topology-schedule event engine:
timed edge add/remove/swap events + seeded synthetic churn, bitwise
replayable across resume),
``--repair`` (self-healing topology repair under churn),
``--devices`` (multi-chip sharding),
``--ws-k/--ws-beta`` (small-world knobs), ``--profile-dir``,
``--telemetry-dir`` (unified run telemetry; render a telemetry dir with
the ``report`` subcommand: ``python -m gossipprotocol_tpu report DIR``),
``--round-budget``/``--trace-cap`` (convergence observatory: analytic
round budgets and per-round trace downsampling; live-tail a running dir
with ``watch DIR``, a serve daemon's whole queue with ``watch
--queue-dir D`` — queue depth, per-worker progress, SLO burn rates —
diff runs with ``report DIR --compare BASELINE``,
track bench history with ``history``; a daemon started with ``--http``
also serves Prometheus text exposition at ``/metrics``),
``--sweep``/``--sweep-seeds`` (mega-sweeps: B lanes of traced-value
variations — seeds, tolerances, activation rates, drop probabilities —
batched through ONE compiled chunk program under vmap; lane *i* is
bitwise the standalone run with lane *i*'s config).
Invalid
input errors loudly — the reference silently
no-ops on unknown topologies (``Program.fs:279``) and prints "option
invalid" on unknown algorithms (``Program.fs:207``).
"""

from __future__ import annotations

import argparse
import os
import sys

def _unit_fraction(s: str) -> float:
    """argparse type for probabilities/fractions in [0, 1).

    Range errors surface as argparse's own usage message + exit 2 —
    never a ValueError traceback from deep inside the fault machinery.
    """
    try:
        v = float(s)
    except ValueError:
        raise argparse.ArgumentTypeError(f"{s!r} is not a number")
    if not 0.0 <= v < 1.0:
        raise argparse.ArgumentTypeError(
            f"{v!r} is out of range — must be in [0.0, 1.0) "
            "(1.0 would kill/drop everything, which nothing survives)"
        )
    return v


def _positive_int(s: str) -> int:
    """argparse type for integer knobs that must be >= 1 (--payload-dim,
    --local-steps, --sgp-samples): range errors are argparse usage errors
    (exit 2), never tracebacks from inside the engine."""
    try:
        v = int(s)
    except ValueError:
        raise argparse.ArgumentTypeError(f"{s!r} is not an integer")
    if v < 1:
        raise argparse.ArgumentTypeError(
            f"{v!r} is out of range — must be >= 1")
    return v


def _positive_float(s: str) -> float:
    """argparse type for strictly-positive float knobs (--lr, --loss-tol)."""
    try:
        v = float(s)
    except ValueError:
        raise argparse.ArgumentTypeError(f"{s!r} is not a number")
    if not v > 0.0:
        raise argparse.ArgumentTypeError(
            f"{v!r} is out of range — must be > 0")
    return v


def _open_unit(s: str) -> float:
    """argparse type for --accel-lambda: a spectral bound strictly inside
    (0, 1) — 0 or 1 would degenerate/stall the Chebyshev recurrence."""
    try:
        v = float(s)
    except ValueError:
        raise argparse.ArgumentTypeError(f"{s!r} is not a number")
    if not 0.0 < v < 1.0:
        raise argparse.ArgumentTypeError(
            f"{v!r} is out of range — must be strictly in (0.0, 1.0)")
    return v


def _build_config(args, algo, fault_schedule, jnp, event_plan=None,
                  alert_quorum=None, telemetry=None):
    """argv -> RunConfig; raises ValueError on invalid combinations
    (caught by main and reported as exit 2, the bad-input contract)."""
    from gossipprotocol_tpu.engine import RunConfig

    round_budget = args.round_budget
    if round_budget is not None and round_budget != "auto":
        try:
            round_budget = int(round_budget)
        except ValueError:
            raise ValueError(
                f"option invalid: --round-budget must be a positive integer "
                f"or 'auto', got {args.round_budget!r}")

    return RunConfig(
        telemetry=telemetry,
        algorithm=algo,
        alert_quorum=alert_quorum,
        dtype=jnp.float64 if args.x64 else jnp.float32,
        seed=args.seed,
        threshold=args.threshold,
        eps=args.eps,
        streak_target=args.streak,
        keep_alive=not args.no_keep_alive,
        semantics=args.semantics,
        predicate=args.predicate,
        tol=args.tol,
        fanout=args.fanout,
        edge_chunks=args.edge_chunks,
        delivery=args.delivery,
        routed_design=args.routed_design or "push",
        rounds_per_kernel=args.rounds_per_kernel,
        payload_wire=args.payload_wire,
        exchange_overlap=args.exchange_overlap,
        plan_cache=args.plan_cache,
        build_workers=args.build_workers,
        value_mode=args.value_mode,
        payload_dim=args.payload_dim,
        workload=args.workload,
        clock=args.clock,
        activation_rate=args.activation_rate,
        groups=args.groups,
        accel=args.accel,
        accel_lambda=args.accel_lambda,
        lr=args.lr,
        local_steps=args.local_steps,
        sgp_samples=args.sgp_samples,
        loss_tol=args.loss_tol,
        max_rounds=args.max_rounds,
        chunk_rounds=args.chunk_rounds,
        seed_node=args.seed_node,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
        fault_schedule=fault_schedule,
        event_plan=event_plan,
        repair=args.repair,
        sentinel=args.sentinel,
        round_budget=round_budget,
    )


def _build_run_topology(args):
    """argv -> (Topology, alert_quorum) with reference-mode population.

    ``--semantics reference`` renders the reference's N+1-actor quirk
    (``Program.fs:169-176`` spawns actors ``0..nodes``; the supervisor
    exits at ``nodes`` Alerts, ``Program.fs:53``):

      * line/full: the wiring loops cover all ``nodes+1`` actors
        (``Program.fs:184-189, 211-216``), so the graph is built one
        node larger and the run converges at ``nodes`` settled — all
        but one.
      * 3D/imp3D: ``nodes`` is first mutated to the cube
        (``Program.fs:239-240``); the wiring covers cube indices only,
        so the extra actor exists but is isolated — rendered as one
        edge-less row, which the birth-exclusion rule keeps out of the
        predicate (the supervisor hears exactly cube Alerts).
      * imp3D additionally draws its extra neighbor with the
        reference's exact off-by-one, directed, self/duplicate-allowing
        rule (:func:`build_imp3d_reference_quirks`).

    Intended mode and the non-reference topologies are untouched.
    """
    from gossipprotocol_tpu.topology import build_topology
    from gossipprotocol_tpu.topology.builders import (
        add_isolated_rows, build_imp3d_reference_quirks,
    )
    from gossipprotocol_tpu.topology.registry import canonical_name

    name = canonical_name(args.topology)
    ref = args.semantics == "reference"

    build = getattr(args, "build", "auto")
    budget = None
    if getattr(args, "build_memory_budget", None) is not None:
        from gossipprotocol_tpu.topology.stream import parse_byte_size

        budget = parse_byte_size(args.build_memory_budget)
    if build == "streamed" or (build == "auto" and budget is not None):
        if ref:
            if build == "streamed":
                raise ValueError(
                    "--build streamed renders the intended-mode graph "
                    "only; the reference-mode population quirks "
                    "(--semantics reference) need the materialized "
                    "builders")
        else:
            return _build_streamed_topology(args, build, budget), None
    if ref and name in ("line", "full"):
        topo = build_topology(name, args.num_nodes + 1)
        return topo, args.num_nodes
    if ref and name == "imp3D":
        return add_isolated_rows(
            build_imp3d_reference_quirks(args.num_nodes, seed=args.seed)
        ), None
    if ref and name == "3D":
        return add_isolated_rows(
            build_topology(name, args.num_nodes)), None
    topo = build_topology(
        args.topology, args.num_nodes,
        seed=args.seed, avg_degree=args.avg_degree, m=args.attach,
        k=args.ws_k, beta=args.ws_beta,
    )
    return topo, None


def _build_streamed_topology(args, build, budget):
    """The out-of-core construction path behind ``--build streamed`` /
    ``--build auto --build-memory-budget``.

    With ``--devices > 1`` on a slice-consuming run configuration the
    build lands a :class:`~gossipprotocol_tpu.topology.stream.\
ShardedTopology` — per-shard CSR slices, peak host RSS O(E/shards +
    budget), byte-identical slices and adjacency digest to the
    materialized build. Everywhere else the edges still stream through
    the bounded spill build, but the final CSR is materialized (the
    single-chip engine needs the global adjacency).
    """
    from gossipprotocol_tpu.topology import stream

    es = stream.edge_stream(
        args.topology, args.num_nodes,
        seed=args.seed, avg_degree=args.avg_degree, m=args.attach,
        k=args.ws_k, beta=args.ws_beta,
    )
    devices = getattr(args, "devices", None)
    sharded = devices is not None and devices > 1
    if sharded and build == "auto":
        # auto only picks the sharded slice form when this run can
        # actually consume it (sharded routed push-sum, no event/repair
        # rewrites); --build streamed skips the check and lets the
        # engine reject incompatible configs loudly
        algo = _ALGO_ALIASES.get(args.algorithm.lower(), args.algorithm)
        sharded = (
            algo != "gossip" and args.fanout == "all"
            and args.delivery in ("routed", "pallas")
            and args.repair == "off"
            and args.event_plan is None and args.churn is None
            and args.value_faults is None
            and args.sentinel in ("off", "on")
        )
    if sharded:
        return stream.build_sharded_topology(
            es, devices, memory_budget=budget,
            build_workers=args.build_workers,
        )
    return stream.topology_from_stream(es, memory_budget=budget)


def resume_argv(argv, checkpoint_dir, attempts_left):
    """argv rewritten for a recovery exec: any prior --resume/--auto-resume
    removed, --resume pinned to the run's own checkpoint dir (omitted when
    no checkpoint landed before the crash — restart from scratch), and
    --auto-resume set to the remaining attempt budget. Pure, for tests."""
    out, skip = [], False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in ("--resume", "--auto-resume"):
            skip = True
            continue
        if a.startswith(("--resume=", "--auto-resume=")) or a == "--restarted":
            continue
        out.append(a)
    if checkpoint_dir is not None:
        out += ["--resume", checkpoint_dir]
    # --restarted keeps --metrics-out in append mode even when no
    # checkpoint landed before the crash (scratch restart): without it
    # the re-exec would reopen the file with mode='w' and silently
    # discard every pre-crash record of the same logical run
    return out + ["--auto-resume", str(attempts_left), "--restarted"]


def _is_runtime_death(e: BaseException) -> bool:
    """The accelerator runtime is gone (not a program error): the axon
    worker's watchdog kill surfaces as JaxRuntimeError UNAVAILABLE, after
    which every call on this client fails the same way (measured)."""
    return type(e).__name__ in ("JaxRuntimeError", "XlaRuntimeError") and (
        "UNAVAILABLE" in str(e)
    )


def _reexec(new_argv) -> int:
    """Replace this process with a fresh CLI invocation.

    A new process gets a new jax client, which reconnects once the worker
    has restarted; 10 s of grace covers the restart window observed on
    this rig. Never returns in production (os.execv); the return type
    exists so tests can monkeypatch it and assert on ``new_argv``.
    """
    import time

    time.sleep(10)
    os.execv(
        sys.executable,
        [sys.executable, "-m", "gossipprotocol_tpu", *new_argv],
    )
    return 1  # pragma: no cover — execv does not return


_ALGO_ALIASES = {
    "gossip": "gossip",
    "push-sum": "push-sum",
    "push_sum": "push-sum",
    "pushsum": "push-sum",
    "push sum": "push-sum",
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gossipprotocol_tpu",
        description="TPU-native gossip / push-sum convergence simulator",
    )
    p.add_argument("num_nodes", type=int)
    p.add_argument("topology", type=str)
    p.add_argument("algorithm", type=str)
    p.add_argument("--backend", default="auto",
                   help="jax platform: auto|tpu|cpu (auto = jax default)")
    p.add_argument("--devices", type=int, default=1,
                   help="shard node state over this many devices (mesh axis 'nodes')")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--threshold", type=int, default=10,
                   help="gossip: hearings to converge (README.md:2)")
    p.add_argument("--eps", type=float, default=1e-10,
                   help="push-sum: |Δ(s/w)| tolerance (Program.fs:116)")
    p.add_argument("--streak", type=int, default=3,
                   help="push-sum: consecutive small-delta rounds (Program.fs:121)")
    p.add_argument("--semantics", choices=["intended", "reference"],
                   default="intended")
    p.add_argument("--predicate", choices=["delta", "global"], default="delta",
                   help="push-sum convergence rule: the reference's intended "
                        "local delta streak, or the sound global "
                        "|s/w - mean| <= tol test (mean known by mass "
                        "conservation)")
    p.add_argument("--tol", type=float, default=1e-4,
                   help="tolerance for --predicate global")
    p.add_argument("--fanout", choices=["one", "all"], default="one",
                   help="push-sum sender: one random neighbor per round "
                        "(the reference's send, Program.fs:128) or the "
                        "fanout-all diffusion variant that converges at "
                        "graph mixing time (required for hub-heavy graphs "
                        "like power-law at scale)")
    p.add_argument("--delivery",
                   choices=["scatter", "invert", "routed", "pallas",
                            "megakernel"],
                   default="scatter",
                   help="push-sum delivery. fanout-one: segment_sum "
                        "scatter-add, or 'invert' — the receiver-side "
                        "gather inversion (single-chip, bounded-degree, no "
                        "faults; measured 9x slower on TPU v5e, a validated "
                        "negative result, see README). fanout-all: "
                        "'routed' replaces the per-edge scatters with "
                        "static Clos routing plans (f32, component-"
                        "closed dead sets; trajectories agree with "
                        "scatter to float accumulation order; measured "
                        "21x faster at 10M power-law). Under --devices N "
                        "each shard runs a directed per-shard plan after "
                        "one all_gather — bitwise the single-chip "
                        "trajectory. 'pallas': the routed pipeline fused "
                        "into bucketed Pallas gather kernels (same plan "
                        "geometry, bitwise equal to 'routed'); under "
                        "--devices N the push design's all_to_all becomes "
                        "per-destination async remote-copy DMAs — see "
                        "README 'Performance'. 'megakernel': the pallas "
                        "path with the whole protocol round fused into "
                        "one VMEM-resident kernel, running "
                        "--rounds-per-kernel rounds per launch "
                        "(single-chip, all-alive, synchronous)")
    p.add_argument("--routed-design", choices=["pull", "push"], default=None,
                   help="sharded routed delivery variant (requires "
                        "--delivery routed with --devices N). 'push' "
                        "(default): owner-computes — each shard expands "
                        "only its owned rows and one all_to_all exchanges "
                        "the edge shares, every table O(E/S + local_n). "
                        "'pull': the round-5 design — all_gather the full "
                        "state, per-shard O(n) plan_in tables; escape "
                        "hatch for graphs the push compiler rejects")
    p.add_argument("--rounds-per-kernel", type=_positive_int, default=1,
                   metavar="K",
                   help="protocol rounds fused into one kernel launch "
                        "(requires --delivery pallas or megakernel; "
                        "--delivery megakernel with K=1 is bitwise the "
                        "pallas path per round). Amortizes launch and "
                        "HBM round-trip overhead; convergence "
                        "trajectories are identical for every K because "
                        "the in-kernel loop freezes once the predicate "
                        "fires")
    p.add_argument("--payload-wire", choices=["f32", "bf16", "int8"],
                   default="f32",
                   help="wire format for the sharded routed-push "
                        "edge-share exchange (requires --devices N with "
                        "--delivery routed/pallas, push design). bf16 "
                        "halves and int8 quarters exchange bytes per "
                        "round; accumulation stays f32 on both ends. "
                        "f32 (default) is the bitwise path")
    p.add_argument("--exchange-overlap", action="store_true",
                   help="double-buffered DMA ring for the sharded "
                        "routed-push exchange: per-destination remote "
                        "copies overlap with the waits instead of "
                        "start-all-then-wait (requires --devices N with "
                        "--delivery routed/pallas, push design; "
                        "bitwise-equal payload bytes)")
    p.add_argument("--plan-cache", type=str, default=None, metavar="DIR",
                   help="routed-delivery plan cache directory (default "
                        "$GOSSIP_TPU_PLAN_CACHE or "
                        "~/.cache/gossipprotocol_tpu/routed-plans; 'none' "
                        "disables). Plans are keyed by the adjacency "
                        "fingerprint; a hit loads bitwise the tables a "
                        "build would produce, skipping the O(E) "
                        "single-core compile (~37 min at 10M nodes)")
    p.add_argument("--build-workers", type=int, default=None, metavar="N",
                   help="processes for cold sharded-plan builds (default "
                        "min(num_shards, cpu_count)). Per-shard plans "
                        "build in a fork pool after a cheap geometry "
                        "fixpoint; plans are bitwise-identical for every "
                        "N, so this only trades build wall-time. 1 forces "
                        "the serial builder")
    p.add_argument("--build", choices=["auto", "materialized", "streamed"],
                   default="auto", metavar="MODE",
                   help="topology construction strategy: 'materialized' "
                        "(the classic global edge list + global CSR), "
                        "'streamed' (out-of-core: generators emit bounded "
                        "edge chunks and the build lands per-shard CSR "
                        "slices directly — peak host RSS O(E/shards) "
                        "instead of O(E); sharded routed designs only), "
                        "or 'auto' (default: materialized, switching to "
                        "streamed when --build-memory-budget is set and "
                        "the run is sharded-routed-compatible). Streamed "
                        "and materialized builds are byte-identical per "
                        "shard and share the adjacency digest, so plan "
                        "caches hit across strategies")
    p.add_argument("--build-memory-budget", type=str, default=None,
                   metavar="BYTES",
                   help="host-memory budget for the streamed build's "
                        "spill buffers (supports K/M/G suffixes, e.g. "
                        "512M). Buffered edge pairs past the budget spill "
                        "to per-shard temp files and are merged at "
                        "finalize. Implies --build streamed under "
                        "--build auto")
    p.add_argument("--value-mode", choices=["scaled", "index"], default="scaled",
                   help="push-sum init: i/N (TPU-safe) or the reference's s_i=i")
    p.add_argument("--payload-dim", type=_positive_int, default=1,
                   metavar="D",
                   help="push-sum payload width: 1 (default) is the scalar "
                        "(s, w) protocol, bitwise the pre-vector program; "
                        "D > 1 averages a per-node [D] vector through the "
                        "same delivery plans (w stays one weight per node). "
                        "Requires push-sum with intended semantics; "
                        "delivery='invert' is scalar-only")
    p.add_argument("--workload", choices=["avg", "sgp", "gala"],
                   default="avg",
                   help="what the push-sum payload carries: 'avg' (plain "
                        "distributed averaging, the default), 'sgp' — "
                        "Stochastic Gradient Push (arXiv:1811.10792): each "
                        "node takes --local-steps gradient steps on its "
                        "private synthetic least-squares shard between "
                        "mixing rounds and the run converges on consensus "
                        "distance AND a train-loss plateau. Requires "
                        "push-sum, --predicate global, --delivery scatter; "
                        "prefer --fanout all (single-target receipt dry "
                        "spells shrink w and destabilize the gradient) — "
                        "or 'gala' (arXiv:1906.04585): SGP actor-learners "
                        "in --groups learner groups, exactly averaged "
                        "inside each group and mixed between groups by "
                        "push-sum gossip (pair with --clock poisson for "
                        "the paper's asynchronous gossip)")
    p.add_argument("--clock", choices=["sync", "poisson"], default="sync",
                   help="execution clock: 'sync' (default) activates every "
                        "node every round — bitwise the pre-async program "
                        "— while 'poisson' samples each round's senders "
                        "i.i.d. with P[active] = 1 - exp(-rate) (the "
                        "thinned continuous-time gossip of "
                        "arXiv:2011.02379; receivers stay passive). "
                        "Seed-deterministic and sharding-invariant: masks "
                        "come from the counter-based run PRNG keyed on "
                        "global ids, like the fault engine's loss windows. "
                        "Incompatible with --accel, --semantics reference, "
                        "and --delivery invert")
    p.add_argument("--activation-rate", type=_positive_float, default=1.0,
                   metavar="R",
                   help="poisson clock rate r > 0: each node's event count "
                        "over T rounds is Binomial(T, 1 - exp(-r)) — "
                        "r = 1 activates ~63%% of nodes per round, small r "
                        "approaches one event per 1/r rounds (ignored "
                        "under --clock sync)")
    p.add_argument("--groups", type=_positive_int, default=1, metavar="G",
                   help="GALA learner-group count (>= 2, must divide the "
                        "node count; requires --workload gala). Groups "
                        "share one activation clock under --clock poisson, "
                        "so a group gossips — or idles — as a unit")
    p.add_argument("--accel", choices=["off", "chebyshev", "epd"],
                   default="off",
                   help="accelerated push-sum averaging for --fanout all "
                        "--delivery scatter (fixed mixing matrix, no "
                        "faults/loss/repair): 'chebyshev' semi-iterative "
                        "weights (spectral bound from --accel-lambda or a "
                        "host power-iteration estimate) or 'epd' — the "
                        "parameter-free Euler-Poisson-Darboux scheme "
                        "(arXiv:2202.10742). Both conserve mass exactly and "
                        "converge in O(1/sqrt(gap)) rounds vs diffusion's "
                        "O(1/gap) — ~2x+ fewer rounds on a 1000-node line")
    p.add_argument("--accel-lambda", type=_open_unit, default=None,
                   metavar="G",
                   help="Chebyshev spectral bound: |lambda_2(W)| of the "
                        "lazy-random-walk mixing matrix, strictly in (0,1). "
                        "Unset = estimate by host power iteration at build "
                        "time (O(iters*E); pass the known value for big "
                        "graphs)")
    p.add_argument("--lr", type=_positive_float, default=0.05,
                   help="SGP local gradient step size (> 0)")
    p.add_argument("--local-steps", type=_positive_int, default=1,
                   metavar="K",
                   help="SGP gradient steps per mixing round (>= 1)")
    p.add_argument("--sgp-samples", type=_positive_int, default=8,
                   metavar="M",
                   help="SGP synthetic least-squares rows per node shard "
                        "(>= 1; m < payload-dim keeps per-node problems "
                        "under-determined so nodes genuinely disagree)")
    p.add_argument("--loss-tol", type=_positive_float, default=1e-5,
                   help="SGP loss-plateau tolerance: converge only when "
                        "|delta mean train loss| <= this on top of the "
                        "consensus predicate")
    p.add_argument("--x64", action="store_true",
                   help="push-sum in float64 (enables jax x64; slower on TPU; "
                        "for numerics — note the delta predicate's early "
                        "firing on slow mixers is intrinsic, not a precision "
                        "artifact; use --predicate global for soundness)")
    p.add_argument("--no-keep-alive", action="store_true",
                   help="disable the Actor2-style rumor keep-alive (Program.fs:141-163)")
    p.add_argument("--max-rounds", type=int, default=1_000_000)
    p.add_argument("--chunk-rounds", type=int, default=None,
                   help="rounds per device call (default: auto by node count)")
    p.add_argument("--seed-node", type=int, default=None)
    p.add_argument("--avg-degree", type=float, default=8.0,
                   help="erdos_renyi mean degree")
    p.add_argument("--attach", type=int, default=4,
                   help="power_law edges per new node (BA m)")
    p.add_argument("--ws-k", type=int, default=6,
                   help="small_world ring-lattice degree (even; k/2 chords "
                        "per side)")
    p.add_argument("--ws-beta", type=float, default=0.1,
                   help="small_world rewiring probability in [0, 1] "
                        "(0 = ring lattice, 1 = random graph). Rewired "
                        "chords that collide (self-loop/duplicate) are "
                        "DROPPED, not redrawn — edge count can dip below "
                        "n*k/2 at high beta, unlike networkx's "
                        "redraw-until-clean Watts-Strogatz")
    p.add_argument("--edge-chunks", type=int, default=1,
                   help="fanout-all delivery in K sequential edge slices "
                        "(K-fold smaller per-edge intermediates; the cure "
                        "for the 100M-node diffusion memory wall)")
    p.add_argument("--metrics-out", type=str, default=None,
                   help="JSONL file for per-chunk metrics records")
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument("--checkpoint-every", type=int, default=0,
                   help="chunks between checkpoints (0 = off)")
    p.add_argument("--resume", type=str, default=None,
                   help="checkpoint file (or dir) to resume from")
    p.add_argument("--auto-resume", type=int, default=0, metavar="N",
                   help="elastic recovery: if the accelerator runtime dies "
                        "mid-run (e.g. a remote TPU worker killed by its "
                        "watchdog) re-exec this CLI from the latest "
                        "checkpoint, at most N times. The dead jax client "
                        "cannot be revived in-process (measured: every "
                        "retry keeps failing UNAVAILABLE), so recovery is "
                        "a fresh process. With --checkpoint-dir/--checkpoint-"
                        "every the run resumes from the latest checkpoint; "
                        "without, it restarts from scratch. Single-process "
                        "only (a single-process multi-device mesh is fine — "
                        "the recovery exec re-owns the whole mesh — but "
                        "multi-process runs are rejected: uncoordinated "
                        "per-process re-execs would race the distributed "
                        "mesh init)")
    p.add_argument("--restarted", action="store_true",
                   help=argparse.SUPPRESS)  # set by recovery re-execs only
    p.add_argument("--fail-fraction", type=_unit_fraction, default=0.0,
                   help="fault injection: kill this fraction of nodes "
                        "(in [0, 1))")
    p.add_argument("--fail-round", type=int, default=0,
                   help="round at which the failures strike")
    p.add_argument("--revive-round", type=int, default=None, metavar="R",
                   help="churn: the --fail-fraction victims rejoin at round "
                        "R with fresh-born state (requires --fail-fraction; "
                        "R must be after --fail-round). Rejoiners count "
                        "toward convergence only once reattached to the "
                        "majority component")
    p.add_argument("--drop-prob", type=_unit_fraction, default=0.0,
                   help="message loss: per-send Bernoulli drop probability "
                        "in [0, 1). Mass-conserving for push-sum (a dropped "
                        "send keeps its (s,w) share at the sender), so "
                        "sum(s)/sum(w) == mean survives any loss rate")
    p.add_argument("--drop-window", type=int, nargs=2, default=None,
                   metavar=("START", "STOP"),
                   help="restrict --drop-prob to rounds [START, STOP) "
                        "(default: the whole run)")
    p.add_argument("--fault-plan", type=str, default=None, metavar="FILE",
                   help="declarative fault schedule (JSON): "
                        '{"kill": [{"round": R, "ids": [...]} | '
                        '{"round": R, "fraction": F, "seed": S}], '
                        '"revive": [{"round": R, "ids": [...]}], '
                        '"loss": [{"start": A, "stop": B, "prob": P}]}. '
                        "Merged with the --fail-*/--revive-*/--drop-* sugar")
    p.add_argument("--event-plan", type=str, default=None, metavar="FILE",
                   help="declarative topology schedule (JSON, events/): "
                        '{"add_edges": [{"round": R, "edges": [[u, v], '
                        '...]}], "remove_edges": [...], "swap_neighbors": '
                        '[{"round": R, "pairs": [[[u1,v1],[u2,v2]], ...]}], '
                        '"churn": {"rate": F, "model": "edge"|"swap", '
                        '"period": P}} — may also carry the kill/revive/'
                        "loss keys of --fault-plan (one document for the "
                        "whole schedule). Events fire at chunk boundaries, "
                        "conserve push-sum mass across every rebuild, and "
                        "replay bitwise across checkpoint resume")
    p.add_argument("--churn", type=str, default=None,
                   metavar="RATE,MODEL[,PERIOD]",
                   help="seeded synthetic churn sugar: every PERIOD rounds "
                        "(default 10) touch RATE of the current edges — "
                        "model 'edge' removes/adds that many edges "
                        "(membership churn), 'swap' crosses edge pairs "
                        "degree-preservingly (mobility). Deterministic from "
                        "--seed; combines with --event-plan")
    p.add_argument("--value-faults", type=str, default=None,
                   metavar="RATE,MODEL[,ROUND]",
                   help="seeded data-fault sugar: at round ROUND (default "
                        "10) corrupt the push-sum s/payload of RATE of the "
                        "nodes — model 'nan'/'inf' poisons them outright, "
                        "'stuck' resets them to their initial value, "
                        "'scale:K' multiplies by K (a silent adversarial "
                        "shift). Victims draw deterministically from --seed "
                        "(shard-count invariant); combines with --event-plan "
                        "(the 'value_faults' JSON key). Push-sum only. Pair "
                        "with --sentinel to detect/contain")
    p.add_argument("--sentinel", nargs="?", const="on", default="off",
                   choices=("off", "on", "quarantine", "rollback"),
                   help="on-device health sentinel folded through the chunk "
                        "loop: per-chunk all-finite check on (s, w, payload)"
                        ", w-positivity, and a host mass-drift tripwire. "
                        "'on' detects and stops; 'quarantine' additionally "
                        "kills the offending rows through the event engine "
                        "(--repair rewire reknits survivors) and continues; "
                        "'rollback' restores the newest checkpoint "
                        "predating the trip (needs --checkpoint-dir/-every) "
                        "and replays with the quarantine inserted. Off = "
                        "zero cost: the compiled programs are bitwise "
                        "identical to a sentinel-free build")
    p.add_argument("--repair", choices=["off", "prune", "rewire"],
                   default="off",
                   help="self-healing topology repair at fault events. "
                        "'prune' drops dead endpoints from the adjacency "
                        "(the majority-partition rule still applies, with "
                        "identical victims); 'rewire' additionally splices "
                        "the orphaned endpoints of dead nodes to each "
                        "other deterministically from --seed (degree-"
                        "preserving; leftovers draw a random live peer), "
                        "so previously-stranded survivors stay in the "
                        "computation. Repair never touches protocol state "
                        "— push-sum mass is conserved across every rewire")
    p.add_argument("--profile-dir", type=str, default=None,
                   help="emit a jax.profiler trace here")
    p.add_argument("--telemetry-dir", type=str, default=None, metavar="DIR",
                   help="unified run telemetry: host spans -> DIR/events.jsonl"
                        " + a Chrome-trace DIR/trace.json, on-device message "
                        "counters folded through every chunk, and a run "
                        "manifest DIR/run.json; render with 'python -m "
                        "gossipprotocol_tpu report DIR'. Unset = zero cost "
                        "(the compiled programs are bitwise identical); set, "
                        "convergence results are STILL bitwise identical — "
                        "counters ride alongside and never feed back")
    p.add_argument("--sweep", type=str, default=None, metavar="PLAN.json",
                   help="mega-sweep plan (JSON): {\"axes\": {\"seed\": "
                        "[...], \"eps\": [...], ...}, \"mode\": \"product\""
                        "|\"zip\"}. Expands axes that vary only traced "
                        "values (seed, seed_node, eps, tol, threshold, "
                        "activation_rate, drop_prob) into B lanes batched "
                        "through ONE compiled chunk program under vmap — "
                        "one plan build, one compile, per-lane convergence "
                        "freezing. Lane i is bitwise the standalone run "
                        "with lane i's config. Structural axes (topology, "
                        "algorithm, delivery, ...) are rejected with exit "
                        "2. Under --devices N only host axes (seed, "
                        "seed_node) are sweepable")
    p.add_argument("--sweep-seeds", type=_positive_int, default=None,
                   metavar="B",
                   help="seed-sweep sugar: B lanes with seeds --seed, "
                        "--seed+1, ... --seed+B-1 (equivalent to --sweep "
                        "with a seed axis; mutually exclusive with it)")
    p.add_argument("--round-budget", type=str, default=None, metavar="N|auto",
                   help="cap the run at N rounds with a structured "
                        "over_budget record instead of grinding to "
                        "--max-rounds; 'auto' derives the cap from the "
                        "analytic round prediction (obs/predict.py): "
                        "budget = 8x the spectral bound for push-sum, 8x "
                        "the log-spread heuristic for gossip")
    p.add_argument("--trace-cap", type=int, default=None, metavar="ROWS",
                   help="per-round trace downsampling cap (default 4096, "
                        "or $GOSSIP_TPU_TRACE_CAP): whenever another ROWS "
                        "trace rows land in DIR/trace.jsonl the round "
                        "stride doubles, bounding the file at "
                        "ROWS*(1+log2(rounds/ROWS)) lines; needs "
                        "--telemetry-dir")
    p.add_argument("--compile-cache", type=str,
                   default=os.environ.get(
                       "GOSSIP_TPU_COMPILE_CACHE",
                       os.path.expanduser("~/.cache/gossipprotocol_tpu/xla"),
                   ),
                   metavar="DIR",
                   help="persistent XLA compilation cache (default shown; "
                        "'' disables). Measured: cached reruns cut "
                        "compile_ms 7x on CPU (1.19 s -> 0.17 s at 100k); "
                        "through the remote-TPU tunnel the reported "
                        "compile window is program-load/upload-bound, so "
                        "savings there are marginal")
    # set by the serve daemon only: identify the run for the telemetry
    # collision guard + manifest, and attach the admission verdict doc
    p.add_argument("--request-id", type=str, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--admission-json", type=str, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--check", action="store_true",
                   help="build and validate the topology, print its shape "
                        "summary, and exit without simulating")
    p.add_argument("--quiet", action="store_true",
                   help="suppress everything except the convergence metric")
    return p


def main(argv=None) -> int:
    effective_argv = list(sys.argv[1:] if argv is None else argv)
    if effective_argv and effective_argv[0] == "report":
        # subcommand dispatch BEFORE argparse: the run parser has three
        # required positionals and would reject `report DIR` with its own
        # usage error
        from gossipprotocol_tpu.obs.report import main as report_main

        return report_main(effective_argv[1:])
    if effective_argv and effective_argv[0] == "watch":
        from gossipprotocol_tpu.obs.watch import main as watch_main

        return watch_main(effective_argv[1:])
    if effective_argv and effective_argv[0] == "history":
        from gossipprotocol_tpu.obs.history import main as history_main

        return history_main(effective_argv[1:])
    if effective_argv and effective_argv[0] == "plan":
        from gossipprotocol_tpu.obs.capacity import main as plan_main

        return plan_main(effective_argv[1:])
    if effective_argv and effective_argv[0] == "serve":
        from gossipprotocol_tpu.serve.supervisor import main as serve_main

        return serve_main(effective_argv[1:])
    if effective_argv and effective_argv[0] == "submit":
        from gossipprotocol_tpu.serve.client import submit_main

        return submit_main(effective_argv[1:])
    if effective_argv and effective_argv[0] == "status":
        from gossipprotocol_tpu.serve.client import status_main

        return status_main(effective_argv[1:])

    args = build_parser().parse_args(argv)

    import jax

    if args.x64:
        jax.config.update("jax_enable_x64", True)

    if args.compile_cache:
        # persistent XLA compile cache (measured: 7x on CPU reruns; the
        # remote-TPU tunnel's compile window is load/upload-bound, so
        # marginal there). Thresholds zeroed so CLI-scale programs cache
        # too. Best-effort: an unwritable HOME (read-only container)
        # must degrade to cache-off, not crash a working CLI.
        # GOSSIP_TPU_COMPILE_CACHE= (empty) disables via the default.
        try:
            os.makedirs(args.compile_cache, exist_ok=True)
        except OSError as e:
            print(f"compile cache disabled ({e})", file=sys.stderr)
        else:
            jax.config.update("jax_compilation_cache_dir", args.compile_cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

    if args.backend != "auto":
        # This image's sitecustomize pre-imports jax, so flipping
        # JAX_PLATFORMS here would be a silent no-op. Select the backend by
        # pinning the default device instead — effective post-import.
        try:
            backend_devices = jax.devices(args.backend)
        except RuntimeError as e:
            print(f"backend {args.backend!r} unavailable: {e}", file=sys.stderr)
            return 2
        jax.config.update("jax_default_device", backend_devices[0])
        backend_name = backend_devices[0].platform
    else:
        backend_name = jax.default_backend()

    from gossipprotocol_tpu.engine import RunConfig, run_simulation, resume_simulation
    from gossipprotocol_tpu.topology import build_topology
    from gossipprotocol_tpu.utils import checkpoint as ckpt
    from gossipprotocol_tpu.utils import faults
    from gossipprotocol_tpu.utils.metrics import (
        JsonlMetricsWriter,
        print_convergence_time,
        print_start_banner,
    )
    from gossipprotocol_tpu.obs import Telemetry, write_manifest
    from gossipprotocol_tpu.obs.telemetry import NULL as _null_telemetry
    from gossipprotocol_tpu.utils.profiling import maybe_trace

    # sweep runs keep counters + manifests but not per-round traces
    # (the trace buffer has no lane story yet — the engine would reject)
    _sweeping = args.sweep is not None or args.sweep_seeds is not None
    try:
        tel = (Telemetry(args.telemetry_dir, trace_cap=args.trace_cap,
                         traces=False if _sweeping else None,
                         run_id=args.request_id)
               if args.telemetry_dir else _null_telemetry)
    except ValueError as e:  # TelemetryDirCollision
        print(str(e), file=sys.stderr)
        return 2
    if args.admission_json and tel.enabled:
        # the daemon's admission verdict rides into the manifest so a
        # telemetry dir stays self-describing about why the run ran
        import json as _json

        try:
            with open(args.admission_json) as fh:
                tel.admission = _json.load(fh)
        except (OSError, _json.JSONDecodeError) as e:
            print(f"warning: --admission-json unreadable ({e})",
                  file=sys.stderr)

    algo = _ALGO_ALIASES.get(args.algorithm.lower())
    if algo is None:
        print(f"option invalid: unknown algorithm {args.algorithm!r} "
              f"(valid: gossip, push-sum)", file=sys.stderr)
        return 2

    try:
        with tel.span("topology_build", topology=args.topology,
                      requested_nodes=args.num_nodes):
            topo, alert_quorum = _build_run_topology(args)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if not args.quiet and topo.num_nodes != args.num_nodes:
        if args.semantics == "reference":
            quorum_note = (f", supervisor exits at {alert_quorum} Alerts"
                           if alert_quorum else "")
            print(f"note: reference population is {topo.num_nodes} actors "
                  f"for {args.num_nodes} requested nodes "
                  f"(Program.fs:169-176,239-240{quorum_note})")
        else:
            print(f"note: {args.topology} rounds {args.num_nodes} up to "
                  f"{topo.num_nodes} nodes (Program.fs:239-240 semantics)")

    if args.check:
        try:
            topo.validate()
        except AssertionError as e:
            print(f"topology invalid: {e}", file=sys.stderr)
            return 2
        deg = topo.degree
        print(f"topology ok: kind={topo.kind} nodes={topo.num_nodes} "
              f"directed_edges={topo.num_directed_edges} "
              f"degree min/mean/max = {int(deg.min())}/"
              f"{float(deg.mean()):.2f}/{int(deg.max())}")
        tel.close()
        return 0

    try:
        schedule = faults.build_schedule(
            topo.num_nodes,
            plan_file=args.fault_plan,
            fail_fraction=args.fail_fraction,
            fail_round=args.fail_round,
            revive_round=args.revive_round,
            drop_prob=args.drop_prob,
            drop_window=tuple(args.drop_window) if args.drop_window else None,
            seed=args.seed,
            max_rounds=args.max_rounds,
        )
    except (ValueError, OSError) as e:
        print(f"fault schedule invalid: {e}", file=sys.stderr)
        return 2

    import dataclasses

    event_plan = None
    try:
        if args.event_plan is not None:
            from gossipprotocol_tpu.events import parse_event_plan

            event_plan, plan_sched = parse_event_plan(
                args.event_plan, topo.num_nodes, seed=args.seed)
            # the plan's kill/revive/loss keys merge with the legacy
            # flags: both spellings compile down to the same engine
            schedule = faults.merge_schedules(schedule, plan_sched)
        if args.churn is not None:
            from gossipprotocol_tpu.events import EventPlan, parse_churn_arg

            spec = parse_churn_arg(args.churn)
            if event_plan is not None and event_plan.churn is not None:
                raise ValueError(
                    "--churn and an event-plan 'churn' generator both "
                    "given — configure one")
            event_plan = dataclasses.replace(
                event_plan if event_plan is not None else EventPlan(),
                churn=spec)
        if args.value_faults is not None:
            from gossipprotocol_tpu.events import (
                EventPlan,
                parse_value_faults_arg,
            )

            vf = parse_value_faults_arg(args.value_faults)
            if event_plan is not None and event_plan.value_faults:
                raise ValueError(
                    "--value-faults and an event-plan 'value_faults' list "
                    "both given — configure one")
            event_plan = dataclasses.replace(
                event_plan if event_plan is not None else EventPlan(),
                value_faults=(vf,))
        if event_plan is not None and topo.implicit_full:
            raise ValueError(
                "event plans need an explicit edge list; the implicit "
                "complete graph has no CSR to rewrite")
    except (ValueError, OSError, KeyError) as e:
        print(f"event plan invalid: {e}", file=sys.stderr)
        return 2

    import jax.numpy as jnp

    try:
        cfg = _build_config(args, algo, schedule, jnp, event_plan=event_plan,
                            alert_quorum=alert_quorum,
                            telemetry=tel if tel.enabled else None)
        if cfg.delivery == "invert":
            # surface the engine's build-time preconditions as clean CLI
            # input errors (exit 2), not tracebacks mid-run
            from gossipprotocol_tpu.engine.driver import require_invertible

            require_invertible(topo)
            if args.devices > 1:
                raise ValueError(
                    "delivery='invert' is single-chip only — drop --devices "
                    "or use delivery='scatter'"
                )
        if args.routed_design is not None and (
                cfg.delivery not in ("routed", "pallas")
                or args.devices <= 1):
            raise ValueError(
                "--routed-design selects between the sharded routed "
                "delivery variants — it needs --delivery routed (or "
                "pallas, push-only) AND --devices N (got delivery=%r, "
                "devices=%d)" % (cfg.delivery, args.devices)
            )
        if (cfg.delivery in ("routed", "pallas", "megakernel")
                and topo.implicit_full):
            raise ValueError(
                f"delivery='{cfg.delivery}' needs an explicit edge list; "
                "the complete graph has none (diffusion on K_n mixes in "
                "one round via two reductions) — use delivery='scatter'"
            )
        if args.devices > 1 and (
                cfg.delivery == "megakernel" or cfg.rounds_per_kernel > 1):
            raise ValueError(
                "the round-loop megakernel is single-chip only (the "
                "in-kernel round has no exchange step) — drop --devices "
                "or --rounds-per-kernel"
            )
        if cfg.payload_wire != "f32" and args.devices <= 1:
            raise ValueError(
                "--payload-wire compresses the sharded edge-share "
                "exchange; a single-chip run has no wire — drop the flag "
                "or add --devices N"
            )
        if cfg.exchange_overlap and args.devices <= 1:
            raise ValueError(
                "--exchange-overlap rewrites the sharded exchange; a "
                "single-chip run has no exchange — drop the flag or add "
                "--devices N"
            )
        if cfg.workload == "gala" and topo.num_nodes % cfg.groups:
            # surfaced here so the divisibility failure is a clean CLI
            # input error (exit 2), not a build-time traceback
            raise ValueError(
                f"--workload gala splits {topo.num_nodes} nodes into "
                f"{cfg.groups} equal groups — the node count must be "
                "divisible by --groups"
            )
        if (args.devices > 1 and algo == "push-sum"
                and args.semantics == "reference"):
            raise ValueError(
                "semantics='reference' push-sum is the single-token walk "
                "(one MainPushSum in flight, Program.fs:128) — a serial "
                "process that cannot shard; drop --devices"
            )
        if args.devices > 1:
            import jax as _jax

            try:
                avail = len(_jax.devices(
                    None if args.backend == "auto" else args.backend))
            except RuntimeError as e:
                raise ValueError(f"backend {args.backend!r}: {e}")
            if avail < args.devices:
                raise ValueError(
                    f"requested {args.devices} devices, only {avail} "
                    f"visible on backend {args.backend!r} (the CPU test "
                    "mesh needs XLA_FLAGS="
                    "--xla_force_host_platform_device_count=N)"
                )
        if args.auto_resume > 0:
            # single-process multi-device meshes re-exec fine (one process
            # owns the whole mesh, so the recovery exec re-initializes it
            # alone); only a *multi-process* runtime is unrecoverable here
            import jax as _jax2

            if _jax2.process_count() > 1:
                raise ValueError(
                    "--auto-resume is single-process only: each process "
                    "would independently re-exec after a fixed grace sleep "
                    "with no barrier before re-initializing the distributed "
                    "runtime, leaving a hung or mismatched mesh — recover "
                    "multi-process runs by relaunching the job from "
                    "--checkpoint-dir"
                )
        if args.sweep is not None or args.sweep_seeds is not None:
            if args.sweep is not None and args.sweep_seeds is not None:
                raise ValueError(
                    "--sweep and --sweep-seeds are two spellings of one "
                    "sweep plan — give exactly one"
                )
            if args.resume:
                raise ValueError(
                    "sweep runs cannot resume from a checkpoint — lanes "
                    "have no per-lane checkpoint story yet"
                )
            from gossipprotocol_tpu.sweep import SweepSpec

            spec = (SweepSpec.from_file(args.sweep)
                    if args.sweep is not None
                    else SweepSpec.from_seeds(args.sweep_seeds,
                                              base_seed=args.seed))
            # riding RunConfig means the capacity preflight below prices
            # HBM as lanes x per-run state automatically
            cfg = dataclasses.replace(cfg, sweep=spec)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    # capacity preflight: refuse a run whose predicted per-device footprint
    # cannot fit before any plan build (no-op where capacity is unknown,
    # i.e. CPU without $GOSSIP_TPU_HBM_BYTES)
    from gossipprotocol_tpu.obs.capacity import CapacityError
    from gossipprotocol_tpu.obs.capacity import preflight as capacity_preflight

    try:
        capacity_preflight(topo, cfg, args.devices, tel)
    except CapacityError as e:
        print(str(e), file=sys.stderr)
        return 2

    if (args.auto_resume > 0 and not args.resume
            and not (args.checkpoint_every and args.checkpoint_dir)):
        # RunConfig warns about the half-configured pair; this is the
        # recovery-specific consequence the user asked for with -N
        print(
            "warning: --auto-resume has no usable checkpoint config "
            "(need both --checkpoint-dir and --checkpoint-every) — a "
            "recovery will RESTART FROM SCRATCH",
            file=sys.stderr,
        )

    state = None
    resume_src = resume_round = None
    if args.resume:
        # fallback chain: a *published* checkpoint can still be unreadable
        # (bitrot, or a torn write on a filesystem where rename is not
        # atomic) — walk the directory's candidates newest-first and fall
        # back to the previous published checkpoint instead of dying on
        # the newest. An explicit file path gets no fallback: the user
        # named that exact checkpoint.
        import zipfile

        cands = (ckpt.candidates(args.resume)
                 if os.path.isdir(args.resume) else [args.resume])
        if not cands:
            print(f"no checkpoint found in {args.resume}", file=sys.stderr)
            return 2
        state = meta = None
        with tel.span("resume_load", target=args.resume):
            for path in cands:
                try:
                    ckpt.peek_meta(path)  # cheap probe before the full load
                    state, meta = ckpt.load(path)
                    break
                except (OSError, ValueError, KeyError,
                        zipfile.BadZipFile) as e:
                    print(
                        f"warning: checkpoint {path} unreadable "
                        f"({type(e).__name__}: {e}); falling back to the "
                        "previous published checkpoint",
                        file=sys.stderr,
                    )
        if state is None:
            print(f"no readable checkpoint in {args.resume}", file=sys.stderr)
            return 2
        resume_src, resume_round = path, int(meta.get("round", -1))
        tel.event("resume_loaded", checkpoint=path, round=resume_round)
        # a checkpoint from a different experiment would "resume" into a
        # plausible-but-wrong run — validate before continuing (and before
        # anything with side effects, like opening the metrics file).
        # trajectory_meta(cfg) is the same mapping save() embedded, so the
        # two sides can never drift.
        problems = [
            # report the value the comparison actually used: the pinned
            # default for a missing legacy field, "all" for a null quorum
            f"{k} {ckpt.stored_value(meta, k)!r} != {v!r}"
            for k, v in ckpt.trajectory_meta(cfg).items()
            # missing fields wildcard (pre-upgrade checkpoint), except the
            # knobs whose absence pins them to their default — see
            # checkpoint.field_matches
            if not ckpt.field_matches(meta, k, v)
        ]
        if meta.get("topology") not in (None, topo.kind):
            problems.append(f"topology {meta.get('topology')!r} != {topo.kind!r}")
        # content hash catches graphs that differ only via builder knobs
        # (--avg-degree, --attach) the kind/size checks can't see
        fp = ckpt.topology_fingerprint(topo)
        if meta.get("adjacency") not in (None, fp):
            problems.append(
                f"adjacency {meta.get('adjacency')!r} != {fp!r} "
                "(different graph, e.g. --avg-degree/--attach changed)"
            )
        if state.alive.shape[0] != topo.num_nodes:
            problems.append(
                f"checkpoint has {state.alive.shape[0]} nodes, run has {topo.num_nodes}"
            )
        if problems:
            print("checkpoint mismatch: " + "; ".join(problems), file=sys.stderr)
            return 2
        if meta.get("quarantines"):
            # quarantines the checkpoint lived through (sentinel
            # containment): replay them into the topology reconstruction
            # so the resumed run continues on the same graph and dead set
            cfg = dataclasses.replace(cfg, quarantine_log=tuple(
                (int(r), tuple(int(i) for i in ids))
                for r, ids in meta["quarantines"]))
        if cfg.delivery == "invert":
            # same build-time precondition the pre-flight block above
            # surfaces for fresh runs: a faulted checkpoint's dead set is
            # not component-closed, so the invert path would be inexact
            from gossipprotocol_tpu.engine.driver import resume_allows_fast

            if not resume_allows_fast(topo, state):
                print(
                    "delivery='invert' cannot resume this checkpoint: its "
                    "dead set is not the birth exclusions (a faulted run) "
                    "— resume with delivery='scatter'",
                    file=sys.stderr,
                )
                return 2

    # append when resuming: the file keeps covering the whole logical run.
    # Semantics are at-least-once — chunks after the last checkpoint are
    # re-run on resume and their records re-emitted — so a resume writes a
    # marker record first; consumers dedup on (round) after the marker.
    writer = (
        JsonlMetricsWriter(
            args.metrics_out,
            mode="a" if (args.resume or args.restarted) else "w",
            stamp_version=tel.enabled)
        if args.metrics_out else None
    )
    if writer:
        cfg = dataclasses.replace(cfg, metrics_callback=writer)
        if state is not None:
            writer({
                "event": "resumed",
                "from_round": int(meta.get("round", -1)),
                "note": "records after this marker may replay rounds "
                        "already present above (at-least-once)",
            })
        elif args.restarted:
            # recovery re-exec with no checkpoint: same file, whole run
            # replays — mark it instead of truncating the pre-crash records
            writer({
                "event": "restarted_from_scratch",
                "note": "recovery without a checkpoint: every round "
                        "replays; records above are the crashed attempt",
            })

    if not args.quiet:
        print_start_banner(algo)

    try:
        # `with tel` makes close (trace flush + end marker) exception-safe:
        # it runs on success, on every error path below, and before the
        # recovery re-exec — the manifest is written afterwards (it only
        # reads accumulated totals, never the event stream)
        with tel:
            if args.profile_dir and tel.enabled:
                # recorded so report/manifest point at the profiler trace;
                # mark_span (depth 1) keeps the phase rollup honest — a
                # depth-0 wrapper would double-count every phase under it
                tel.profile_dir = args.profile_dir
            _prof_start = tel.wall_s()
            with maybe_trace(args.profile_dir):
                if args.devices > 1:
                    from gossipprotocol_tpu.parallel import (
                        run_simulation_sharded,
                    )

                    result = run_simulation_sharded(
                        topo, cfg, num_devices=args.devices,
                        initial_state=state,
                        backend=(None if args.backend == "auto"
                                 else args.backend),
                    )
                elif state is not None:
                    result = resume_simulation(topo, cfg, state)
                else:
                    result = run_simulation(topo, cfg)
            if args.profile_dir:
                tel.mark_span("profiler_trace", _prof_start,
                              tel.wall_s() - _prof_start,
                              trace_dir=args.profile_dir)
    except Exception as e:
        # routed-delivery build rejections and sweep-envelope violations
        # are user input errors that can only surface once the engine
        # sees the full config — same loud-exit-2 contract as the
        # preflight checks above
        from gossipprotocol_tpu.ops.delivery import RoutedConfigError
        from gossipprotocol_tpu.sweep.engine import SweepConfigError

        if isinstance(e, (RoutedConfigError, SweepConfigError)):
            if writer:
                writer.close()
            write_manifest(tel, cfg, topo, None, backend=backend_name,
                           num_devices=args.devices, error=str(e))
            print(str(e), file=sys.stderr)
            return 2
        if not (_is_runtime_death(e) and args.auto_resume > 0):
            raise
        # elastic recovery (SURVEY.md §5.3): the client is unrecoverable
        # in-process, so flush side channels and re-exec from the latest
        # checkpoint (or from scratch if none landed yet)
        if writer:
            writer.close()
        # pick the FURTHEST-ALONG *compatible* candidate checkpoint: the
        # newest in --checkpoint-dir (this run's own, usually) vs the one
        # the user originally resumed from. Compatibility (trajectory
        # fields + graph fingerprint, the same rules the resume block
        # enforces) is checked BEFORE the round comparison — a stale
        # leftover in the dir from a different experiment must neither
        # shadow real progress nor win only to trip resume validation
        # and end the recovery chain; --resume must never be silently
        # discarded either way.
        traj = ckpt.trajectory_meta(cfg)
        fp = ckpt.topology_fingerprint(topo)

        def _round_of(path_or_dir):
            if not path_or_dir:
                return None
            paths = (ckpt.candidates(path_or_dir)
                     if os.path.isdir(path_or_dir) else [path_or_dir])
            for path in paths:
                if not os.path.exists(path):
                    continue
                try:
                    m = ckpt.peek_meta(path)
                except Exception:
                    # unreadable (torn write/bitrot) — fall back to the
                    # previous published candidate, like the resume block
                    continue
                compatible = (
                    all(ckpt.field_matches(m, k, v) for k, v in traj.items())
                    and m.get("topology") in (None, topo.kind)
                    and m.get("adjacency") in (None, fp)
                )
                # the first READABLE candidate decides: an incompatible
                # one means this target holds a different experiment
                return int(m.get("round", -1)) if compatible else None
            return None

        candidates = [
            (r, target)
            for target in (args.checkpoint_dir, args.resume)
            if (r := _round_of(target)) is not None
        ]
        # key on round only: ties keep list order, preferring the run's
        # own checkpoint dir
        resume_target = (
            max(candidates, key=lambda t: t[0])[1] if candidates else None
        )
        effective = list(sys.argv[1:]) if argv is None else list(argv)
        new_argv = resume_argv(effective, resume_target, args.auto_resume - 1)
        print(
            f"accelerator runtime died ({type(e).__name__}); "
            + (f"resuming from {resume_target}" if resume_target
               else "no checkpoint yet — restarting from scratch")
            + f", {args.auto_resume - 1} recovery attempts left",
            file=sys.stderr,
        )
        write_manifest(
            tel, cfg, topo, None, backend=backend_name,
            num_devices=args.devices, resumed_from=resume_src,
            resume_round=resume_round,
            error=f"accelerator runtime died: {type(e).__name__}",
        )
        return _reexec(new_argv)

    if writer:
        writer.close()
    manifest_path = write_manifest(
        tel, cfg, topo, result, backend=backend_name,
        num_devices=args.devices, resumed_from=resume_src,
        resume_round=resume_round,
    )

    print_convergence_time(result.wall_ms)
    if not args.quiet:
        print(f"rounds: {result.rounds}  converged: {result.converged}  "
              f"nodes: {result.num_nodes}  compile: {result.compile_ms:.1f} ms  "
              f"devices: {args.devices}  backend: {backend_name}")
        lanes = getattr(result, "lanes", 0)
        if lanes:
            done = sum(1 for lr in result.lane_records if lr["converged"])
            rounds = sorted(lr["rounds"] for lr in result.lane_records)
            print(f"sweep: {lanes} lanes, {done} converged, lane rounds "
                  f"{rounds[0]}..{rounds[-1]}  "
                  f"(amortized {result.wall_ms / lanes:.2f} ms/lane)")
        err = result.estimate_error
        if err is not None:
            print(f"push-sum max |s/w - mean| = {err:.3e}")
        if manifest_path:
            print(f"telemetry: {tel.dir} (render: python -m "
                  f"gossipprotocol_tpu report {tel.dir})")
    if getattr(result, "stopped", None) == "drain":
        # graceful stop (serve drain): neither converged nor failed —
        # exit 3 so a supervisor can tell "paused, checkpoint saved"
        # from "ran its course without converging" (exit 1)
        print(f"drained at round {result.rounds} (checkpoint "
              f"{'saved' if result.checkpoints else 'not configured'})",
              file=sys.stderr)
        return 3
    return 0 if result.converged else 1


if __name__ == "__main__":
    raise SystemExit(main())
