"""Stochastic Gradient Push (arXiv:1811.10792) on the gossip engine.

One SGP round is (i) a local gradient step and (ii) one push-sum mixing
round, composed so the *existing* delivery machinery runs unchanged:

    zₜ   = xₜ / wₜ                      (de-biased estimate — state.ratio)
    z′   = zₜ − lr·∇Fᵢ(zₜ)  (× local_steps, full local batch)
    xₜ₊½ = xₜ + (z′ − zₜ)              (gradient applied to the numerator)
    (xₜ₊₁, wₜ₊₁) = push-sum mix of (xₜ₊½, wₜ)

The de-bias-then-update form is the paper's: gradients are evaluated at
the unbiased estimate ``z`` while the *biased* numerator ``x`` carries
the update through the mass-weighted mixing. With ``local_steps = k``,
``z′ − z = −lr · Σⱼ ∇Fᵢ(z⁽ʲ⁾)`` along the local trajectory.

Convergence is consensus-distance AND loss-plateau: the mixing core's
``global`` predicate certifies every node within ``tol`` of the current
mass-weighted mean (consensus), and on top of that the mean train loss
must have moved ≤ ``loss_tol`` since the previous round — consensus
alone would fire while the optimizer is still descending.

The wrapper is engine-agnostic: it has the same ``(state, nbrs, key,
**kw)`` shape as every round core, with the :class:`~gossipprotocol_tpu.
learn.data.SGPBundle` riding the ``nbrs`` slot, so both the single-chip
chunk runner and the ``shard_map`` engine drive it unmodified.
"""

from __future__ import annotations

import jax.numpy as jnp

from gossipprotocol_tpu.learn.data import lsq_node_grad, lsq_node_loss
from gossipprotocol_tpu.protocols.pushsum import sum0
from gossipprotocol_tpu.protocols.state import SGPState


def sgp_init(
    num_nodes: int,
    payload_dim: int,
    dtype=jnp.float32,
    real_nodes: int | None = None,
) -> SGPState:
    """All nodes start at x₀ = 0, w₀ = 1 (phantom padding rows at 0, 0).

    Zero init keeps the start deterministic and shared — SGP's consensus
    term then only has to track the *gradient-induced* disagreement, and
    the initial loss is the data variance ½·mean(b²).
    """
    n = real_nodes if real_nodes is not None else num_nodes
    w = jnp.ones(num_nodes, dtype)
    alive = jnp.ones(num_nodes, bool)
    converged = jnp.zeros(num_nodes, bool)
    if num_nodes > n:
        phantom = jnp.arange(num_nodes) >= n
        w = jnp.where(phantom, 0, w)
        alive = alive & ~phantom
        converged = converged | phantom
    z = jnp.zeros((num_nodes, payload_dim), dtype)
    return SGPState(
        # distinct buffers: the chunk runner donates the whole state, and
        # XLA rejects the same buffer donated twice
        s=z,
        w=w,
        ratio=jnp.copy(z),
        streak=jnp.zeros(num_nodes, jnp.int32),
        converged=converged,
        alive=alive,
        round=jnp.int32(0),
        # ∞ sentinel: the plateau test |Δloss| <= loss_tol can never fire
        # on the first real round
        loss=jnp.asarray(jnp.inf, jnp.float32),
    )


def sgp_trace_row(state: SGPState, *, all_sum=sum0, all_max=None):
    """Observatory trace row for SGP: push-sum's consensus/mass columns
    plus the mean train loss the state already carries (replicated by the
    ``all_sum`` inside the round core, so no extra reduction is needed —
    ``pushsum_trace_row`` picks the ``loss`` field up via ``hasattr``)."""
    import jax.numpy as _jnp

    from gossipprotocol_tpu.protocols.pushsum import pushsum_trace_row

    if all_max is None:
        all_max = _jnp.max
    return pushsum_trace_row(state, all_sum=all_sum, all_max=all_max)


def make_sgp_core(mix_core, *, lr: float, local_steps: int,
                  loss_tol: float, all_sum=sum0):
    """Wrap a fully-bound push-sum mixing core into an SGP round core.

    ``mix_core(state, nbrs, base_key, **kw)`` is any of the engine's
    round cores (fanout-one scatter or fanout-all diffusion, single-chip
    or shard_map-injected); the returned core has the identical calling
    shape but expects an ``SGPBundle`` in the ``nbrs`` slot.
    """

    def core(state: SGPState, nbrs, base_key, **kw) -> SGPState:
        bundle = nbrs  # SGPBundle riding the engine's nbrs slot
        dt = state.s.dtype
        step = jnp.asarray(lr, dt)
        z0 = state.ratio
        z = z0
        for _ in range(local_steps):
            z = z - step * lsq_node_grad(bundle.A, bundle.b, z)
        live = state.alive[:, None]
        x_half = state.s + jnp.where(live, z - z0, 0)
        st = mix_core(state._replace(s=x_half), bundle.nbrs, base_key, **kw)
        node_loss = lsq_node_loss(bundle.A, bundle.b, st.ratio)
        alive_f = st.alive.astype(dt)
        mean_loss = (
            all_sum(jnp.where(st.alive, node_loss, 0))
            / jnp.maximum(all_sum(alive_f), jnp.asarray(1, dt))
        ).astype(jnp.float32)
        plateau = jnp.abs(mean_loss - state.loss) <= loss_tol
        return st._replace(converged=st.converged & plateau, loss=mean_loss)

    return core
