"""GALA: gossip-based actor-learner groups (arXiv:1906.04585).

GALA organizes learners into G *groups*: inside a group the actor-
learners share gradients and keep an exactly-synchronized model (the
paper's learners within one GALA node); *between* groups, models mix
only through asynchronous push-sum gossip, so no global barrier ever
forms. One engine round is

    z′    = local SGP gradient step(s) on each node's private shard
    x_half = x + (z′ − z)                      (same de-bias as learn/sgp)
    (x̄, w̄) = exact per-group average of (x_half, w) over alive members
    (x₊₁, w₊₁) = push-sum mixing round of (x̄, w̄)   (inter-group gossip)

The intra-group average is mass-preserving (each alive member gets the
group mean; the group's Σs, Σw are unchanged), so every push-sum
invariant — conservation, the global predicate's achievable mean,
``estimate_error`` — survives. Asynchrony comes from the activation
clock (:mod:`gossipprotocol_tpu.async_`): the driver builds the poisson
clock spec with ``id_div = group_size``, so a whole group shares one
clock and gossips (or stays silent) as a unit — the paper's per-node
(per-group, in our mapping) asynchronous gossip.

Engine-agnostic like the SGP wrapper: the returned core has the
``(state, nbrs, key, **kw)`` shape, reuses :class:`~gossipprotocol_tpu.
learn.data.SGPBundle` on the ``nbrs`` slot and ``SGPState`` (the loss
scalar rides along), so checkpoints, trace rows, and both engines work
unmodified. Group membership is by global row id (``gid // group_size``),
recovered from the engine kwargs (``gids`` on the sharded fanout-one
path, ``row_offset`` on sharded diffusion, neither single-chip), so the
grouping — hence the trajectory — is sharding-invariant.

Convergence is SGP's: consensus distance (the mixing core's ``global``
predicate, now certifying *inter-group* agreement since members are
exactly equal) AND a plateau of the mean train loss.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gossipprotocol_tpu.learn.data import lsq_node_grad, lsq_node_loss
from gossipprotocol_tpu.protocols.pushsum import sum0
from gossipprotocol_tpu.protocols.state import SGPState


def make_gala_core(mix_core, *, num_groups: int, group_size: int,
                   lr: float, local_steps: int, loss_tol: float,
                   all_sum=sum0, group_sum=None):
    """Wrap a fully-bound push-sum mixing core into a GALA round core.

    ``group_sum(x, group_ids) -> [G, ...]`` is the cross-row group
    reduction — a plain ``segment_sum`` single-chip (default), a
    ``psum``'d ``segment_sum`` closure under ``shard_map`` (G is small,
    so the [G, d] all-reduce is noise next to the round's collectives).
    Its result must be replicated across shards, like ``all_sum``'s.
    """
    if group_sum is None:
        def group_sum(x, group_ids):
            return jax.ops.segment_sum(x, group_ids,
                                       num_segments=num_groups)

    def core(state: SGPState, nbrs, base_key, **kw) -> SGPState:
        bundle = nbrs  # SGPBundle riding the engine's nbrs slot
        dt = state.s.dtype
        step = jnp.asarray(lr, dt)
        z0 = state.ratio
        z = z0
        for _ in range(local_steps):
            z = z - step * lsq_node_grad(bundle.A, bundle.b, z)
        live = state.alive[:, None]
        x_half = state.s + jnp.where(live, z - z0, 0)

        # intra-group exact averaging over alive members: phantom padding
        # rows (dead, zero mass) must neither receive mass — it would
        # strand — nor skew the mean, so they are excluded on both sides.
        # Row ids are global (see module docstring), clipped so padding
        # rows beyond n fold into the last group as harmless zeros.
        rows = state.w.shape[0]
        gid_rows = kw.get("gids")
        if gid_rows is None:
            gid_rows = kw.get("row_offset", 0) + jnp.arange(
                rows, dtype=jnp.int32)
        group_ids = jnp.clip(
            gid_rows // jnp.int32(group_size), 0, num_groups - 1)
        alive_f = state.alive.astype(dt)
        g_cnt = jnp.maximum(group_sum(alive_f, group_ids),
                            jnp.asarray(1, dt))                   # [G]
        g_s = group_sum(jnp.where(live, x_half, 0), group_ids)    # [G, d]
        g_w = group_sum(jnp.where(state.alive, state.w, 0),
                        group_ids)                                # [G]
        x_avg = jnp.where(
            live, (g_s / g_cnt[:, None])[group_ids], x_half)
        w_avg = jnp.where(
            state.alive, (g_w / g_cnt)[group_ids], state.w)

        st = mix_core(state._replace(s=x_avg, w=w_avg),
                      bundle.nbrs, base_key, **kw)

        node_loss = lsq_node_loss(bundle.A, bundle.b, st.ratio)
        alive2 = st.alive.astype(dt)
        mean_loss = (
            all_sum(jnp.where(st.alive, node_loss, 0))
            / jnp.maximum(all_sum(alive2), jnp.asarray(1, dt))
        ).astype(jnp.float32)
        plateau = jnp.abs(mean_loss - state.loss) <= loss_tol
        return st._replace(converged=st.converged & plateau,
                           loss=mean_loss)

    return core
