"""Synthetic per-node data shards for the decentralized-learning workload.

Stochastic Gradient Push trains one *global* model over data scattered
across nodes: node ``i`` holds a private shard it alone computes
gradients on, and push-sum averaging does the rest. The synthetic task
here is least squares — node ``i`` draws ``m`` rows ``(Aᵢ, bᵢ)`` with
``bᵢ = Aᵢ·θ* + noise`` against one shared ground truth ``θ*``, so

    F(z) = (1/n) Σᵢ Fᵢ(z),   Fᵢ(z) = (1/2m) ‖Aᵢ z − bᵢ‖²

is strongly convex with a known minimizer near ``θ*``, the per-node
optima genuinely *disagree* (each shard alone is under-determined for
``m < d``), and every quantity is seed-deterministic — the fixed-seed →
identical-final-loss acceptance gate needs no tolerance.

Everything is generated host-side with ``numpy.default_rng`` (counter
PRNG, platform-stable) and shipped to the device once; the per-round
gradient math in :mod:`learn.sgp` is pure row-local einsum, so the
arrays shard over the node axis exactly like the neighbor tables.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# fold applied to the run seed so the data draw never collides with the
# protocol's neighbor/loss key streams
DATA_SEED_FOLD = 0xDA7A


class SGPBundle(NamedTuple):
    """What an SGP round needs per node, threaded through the engine's
    ``nbrs`` slot (it is a pytree, so ``shard_map`` in_specs / device_put
    shard the data rows exactly like the state rows).

    ``nbrs`` is whatever neighbor structure the selected delivery wants
    (CSR/dense tables for fanout-one, ``DiffusionEdges`` for fanout-all);
    the SGP wrapper unwraps it before delegating to the mixing core.
    """

    nbrs: Any           # delivery neighbor pytree (or None: implicit full)
    A: jax.Array        # float[rows, m, d]  per-node design matrix shard
    b: jax.Array        # float[rows, m]     per-node targets


def make_least_squares(
    num_nodes: int,
    payload_dim: int,
    samples: int,
    seed: int,
    dtype=np.float32,
    noise: float = 0.01,
    rows: int | None = None,
):
    """Seed-deterministic shards: ``(A, b, theta_star)`` as numpy arrays.

    ``rows`` pads the node axis (sharding): padding rows get zero data —
    their gradients are identically zero, mirroring how phantom rows
    carry no mass.
    """
    rng = np.random.default_rng(np.uint64(seed) ^ np.uint64(DATA_SEED_FOLD))
    theta = rng.standard_normal(payload_dim)
    a_full = rng.standard_normal((num_nodes, samples, payload_dim))
    b_full = a_full @ theta + noise * rng.standard_normal((num_nodes, samples))
    rows = num_nodes if rows is None else rows
    a_out = np.zeros((rows, samples, payload_dim), dtype=dtype)
    b_out = np.zeros((rows, samples), dtype=dtype)
    a_out[:num_nodes] = a_full
    b_out[:num_nodes] = b_full
    return a_out, b_out, theta.astype(dtype)


def lsq_node_loss(a: jax.Array, b: jax.Array, z: jax.Array) -> jax.Array:
    """Per-node loss Fᵢ(zᵢ) = (1/2m) ‖Aᵢ zᵢ − bᵢ‖² → float[rows]."""
    resid = jnp.einsum("nmd,nd->nm", a, z) - b
    return 0.5 * jnp.mean(resid * resid, axis=1)


def lsq_node_grad(a: jax.Array, b: jax.Array, z: jax.Array) -> jax.Array:
    """Per-node gradient ∇Fᵢ(zᵢ) = (1/m) Aᵢᵀ(Aᵢ zᵢ − bᵢ) → float[rows, d]."""
    resid = jnp.einsum("nmd,nd->nm", a, z) - b
    return jnp.einsum("nmd,nm->nd", a, resid) / a.shape[1]
