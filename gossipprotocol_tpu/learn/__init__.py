from gossipprotocol_tpu.learn.data import (
    SGPBundle,
    make_least_squares,
    lsq_node_loss,
    lsq_node_grad,
)
from gossipprotocol_tpu.learn.gala import make_gala_core
from gossipprotocol_tpu.learn.sgp import make_sgp_core, sgp_init

__all__ = [
    "SGPBundle",
    "make_least_squares",
    "lsq_node_loss",
    "lsq_node_grad",
    "make_gala_core",
    "make_sgp_core",
    "sgp_init",
]
