"""Unified topology-schedule event engine.

One declarative surface for everything that changes the world at chunk
boundaries: fault strikes (utils/faults.py), overlay repair
(topology/repair.py), and edge-level churn — timed ``add_edges`` /
``remove_edges`` / ``swap_neighbors`` plus a seeded synthetic churn
generator. The legacy ``--fault-plan``/``--fail-*``/``--repair`` flags
compile down to this engine; event plans add the new axis on top.

* :mod:`gossipprotocol_tpu.events.plan` — the declarative data model
  (:class:`EventPlan`, :class:`ChurnSpec`), JSON parsing, churn
  generation, and edge-event application;
* :mod:`gossipprotocol_tpu.events.engine` — :class:`HostEvents`, the
  chunk-boundary pipeline the drive loop executes, and the bitwise
  resume replay (:func:`replay_topology`).
"""

from gossipprotocol_tpu.events.plan import (  # noqa: F401
    CHURN_MODELS,
    VALUE_FAULT_MODELS,
    ChurnSpec,
    EventPlan,
    ValueFaultSpec,
    apply_edge_events,
    as_plan,
    generate_churn,
    parse_churn_arg,
    parse_event_plan,
    parse_value_faults_arg,
    value_fault_ids,
)
from gossipprotocol_tpu.events.engine import (  # noqa: F401
    HostEvents,
    replay_topology,
    replay_topology_events,
)

__all__ = [
    "CHURN_MODELS",
    "VALUE_FAULT_MODELS",
    "ChurnSpec",
    "EventPlan",
    "HostEvents",
    "ValueFaultSpec",
    "apply_edge_events",
    "as_plan",
    "generate_churn",
    "parse_churn_arg",
    "parse_event_plan",
    "parse_value_faults_arg",
    "replay_topology",
    "replay_topology_events",
    "value_fault_ids",
]
