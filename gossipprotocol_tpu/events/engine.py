"""The unified host-event engine: one chunk-boundary pipeline for fault
strikes, overlay repair, and topology-schedule (churn) events.

Before this module, the driver's chunk loop carried the fault/repair
branching inline (kills -> revives -> repair -> partition rule -> device
alive diff -> rebuild + mass assertion). :class:`HostEvents` owns that
pipeline and extends it with the edge-level events of
:mod:`gossipprotocol_tpu.events.plan`, executed in one pass per event
round:

1. **strikes** — due kills then due revives flip the host alive mask
   (utils/faults.py semantics, byte-for-byte the legacy order);
2. **edge events** — explicit ``add/remove/swap`` entries plus generated
   churn, applied per due round in ascending order against the *current*
   adjacency (the environment changes the graph);
3. **repair** — the configured policy responds to the post-event graph
   (topology/repair.py, same rng keying as before);
4. **one partition-rule pass** — unreachable-from-majority == dead,
   against the final adjacency (``apply_partition_rule``; with no churn
   and ``repair='off'`` this is exactly the legacy
   ``kill_disconnected(birth_topo, ...)`` call, since ``run_topo`` never
   leaves the birth adjacency);
5. **rebirth + device diff + rebuild** — revived rows reset to
   fresh-born state, the alive diff scatters onto the device buffer, and
   any adjacency change triggers the engine rebuild hook under the same
   float64 mass-conservation assertion repair always ran under.

Every adjacency change flows through the engine's ``rebuild`` hook, so
the sharded routed-push path patches its delivery plans incrementally
(:func:`gossipprotocol_tpu.ops.sharddelivery.patch_shard_push_deliveries`)
for churn exactly as it already did for repair.

Resume: :func:`replay_topology_events` reconstructs the adjacency a
checkpoint lived through by replaying strikes + edge events + repair +
partition per event round — bitwise, because explicit events are literal,
generated churn is counter-keyed per round, and
:func:`~gossipprotocol_tpu.events.plan.apply_edge_events` rebuilds
canonical CSRs.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from gossipprotocol_tpu.events import plan as plan_mod
from gossipprotocol_tpu.topology.base import Topology


def _due_churn_rounds(plan, start: Optional[int], upto: int):
    """Churn rounds in ``[start, upto]`` (ascending); ``start`` is the
    engine's next-unfired churn pointer."""
    if plan.churn is None or start is None or start > upto:
        return []
    return list(range(start, upto + 1, int(plan.churn.period)))


def _apply_round_edge_events(run_topo, plan, r: int, *, run_seed: int,
                             consume=None):
    """Apply round ``r``'s edge events (explicit + generated churn) to
    ``run_topo``. The single source of truth shared by the live engine
    and the resume replay — they cannot drift apart.

    ``consume`` optionally maps the mutable (adds, removes, swaps) dicts
    the live engine pops fired events from; the replay passes None and
    reads the plan directly. Returns ``(new_topo, stats, generated)``.
    """
    if consume is not None:
        adds, removes, swaps = consume
        ex_add = adds.pop(r, None)
        ex_rem = removes.pop(r, None)
        ex_swp = swaps.pop(r, None)
    else:
        ex_add = plan.adds.get(r)
        ex_rem = plan.removes.get(r)
        ex_swp = plan.swaps.get(r)
    generated = False
    if plan.churn is not None and r >= plan.churn.period \
            and r % int(plan.churn.period) == 0:
        g_rem, g_add, g_swp = plan_mod.generate_churn(
            run_topo, plan.churn, run_seed=run_seed, event_round=r)
        generated = bool(g_rem.size or g_add.size or g_swp.size)

        def cat(ex, gen):
            if ex is None:
                return gen if gen.size else None
            ex = np.asarray(ex, np.int64).reshape(-1, gen.shape[1])
            return np.concatenate([ex, gen]) if gen.size else ex

        ex_rem = cat(ex_rem, g_rem)
        ex_add = cat(ex_add, g_add)
        ex_swp = cat(ex_swp, g_swp)
    return (*plan_mod.apply_edge_events(
        run_topo, removes=ex_rem, adds=ex_add, swaps=ex_swp), generated)


class HostEvents:
    """All chunk-boundary host events of one run, in firing order.

    Constructed at drive-loop entry with the resume round: strictly-past
    events are pruned exactly like the legacy driver did (a checkpoint at
    round C reflects every event with r < C, never r == C — re-firing a
    kill could re-kill a node revived since, and a revive reset is not
    idempotent). The drive loop asks :meth:`next_round` to stop each
    chunk at the next event and calls :meth:`fire` between chunks.
    """

    def __init__(self, topo: Topology, cfg, start_round: int, tel):
        sched = cfg.schedule
        self.plan = cfg.events
        if self.plan.has_events and topo.implicit_full:
            raise ValueError(
                "event plans need an explicit edge list; the implicit "
                "complete graph has no CSR to rewrite")
        self.plan.validate(topo.num_nodes)
        self.topo = topo
        self.cfg = cfg
        self.tel = tel
        keep = lambda ev: {  # noqa: E731
            int(r): np.asarray(v, dtype=np.int64)
            for r, v in ev.items() if int(r) >= start_round
        }
        self.kills = keep(sched.kills)
        self.revives = keep(sched.revives)
        self.adds = keep(self.plan.adds)
        self.removes = keep(self.plan.removes)
        self.swaps = keep(self.plan.swaps)
        # value-fault injections by round (a checkpoint at C reflects the
        # corruption of every fault with r < C — it is in the state)
        self.value_faults: dict = {}
        for vf in self.plan.value_faults:
            if int(vf.round) >= start_round:
                self.value_faults.setdefault(int(vf.round), []).append(vf)
        # next unfired churn round (None without a generator); a resumed
        # run starts at the first multiple of the period >= start_round
        self._churn_next = self.plan.next_churn_round(start_round)

    # ---- scheduling ----------------------------------------------------

    def next_round(self, default: int) -> int:
        """Round of the next pending event; the drive loop stops each
        chunk exactly here so no event can be skipped."""
        cands = [*self.kills, *self.revives, *self.adds, *self.removes,
                 *self.swaps, *self.value_faults]
        if self._churn_next is not None:
            cands.append(self._churn_next)
        return min(cands, default=default)

    def due(self, cur_round: int) -> bool:
        return self.next_round(cur_round + 1) <= cur_round

    # ---- execution -----------------------------------------------------

    def fire(self, state, run_topo, cur_round: int, rebuild):
        """Fire everything due at ``cur_round`` through the unified
        pipeline. Returns ``(state, run_topo, new_step_or_None, records,
        reborn_count)`` — ``new_step`` is the recompiled chunk step when
        the adjacency changed (the caller swaps it in and re-anchors its
        mass baseline if ``reborn_count``)."""
        from gossipprotocol_tpu.topology import repair as repair_mod
        from gossipprotocol_tpu.utils import checkpoint as ckpt_mod
        from gossipprotocol_tpu.utils import faults as faults_mod

        cfg, topo, tel = self.cfg, self.topo, self.tel
        due_k = sorted(r for r in self.kills if r <= cur_round)
        due_r = sorted(r for r in self.revives if r <= cur_round)
        due_e = sorted({r for ev in (self.adds, self.removes, self.swaps)
                        for r in ev if r <= cur_round}
                       | set(_due_churn_rounds(self.plan, self._churn_next,
                                               cur_round)))
        due_v = sorted(r for r in self.value_faults if r <= cur_round)
        span_attrs = dict(round=cur_round, kills=len(due_k),
                          revives=len(due_r))
        if due_e:
            span_attrs["edge_events"] = len(due_e)
        if due_v:
            span_attrs["value_faults"] = len(due_v)
        with tel.span("fault_event", **span_attrs):
            alive_host = np.array(ckpt_mod.fetch_host(state.alive))
            before = alive_host.copy()
            req_revive = (np.concatenate([self.revives[r] for r in due_r])
                          if due_r else np.empty(0, np.int64))
            for r in due_k:
                alive_host[self.kills.pop(r)] = False
            for r in due_r:
                alive_host[self.revives.pop(r)] = True

            # edge events per due round in ascending order, against the
            # evolving adjacency — identical to the resume replay's
            # per-round loop (they share _apply_round_edge_events)
            edge_stats = {"changed": False, "edges_added": 0,
                          "edges_removed": 0, "edges_swapped": 0,
                          "edges_skipped": 0}
            generated = False
            for r in due_e:
                run_topo, st, gen = _apply_round_edge_events(
                    run_topo, self.plan, r, run_seed=cfg.seed,
                    consume=(self.adds, self.removes, self.swaps))
                generated |= gen
                edge_stats["changed"] |= st["changed"]
                for k in ("edges_added", "edges_removed", "edges_swapped",
                          "edges_skipped"):
                    edge_stats[k] += st[k]
            if due_e and self._churn_next is not None:
                self._churn_next = self.plan.next_churn_round(cur_round + 1)

            repair_stats = None
            if cfg.repair != "off":
                # self-healing (topology/repair.py): prune dead endpoints
                # from the CSR (rewire additionally re-splices survivors)
                # — responding to the post-churn graph
                run_topo, repair_stats = repair_mod.repair_topology(
                    run_topo, alive_host[: topo.num_nodes], cfg.repair,
                    run_seed=cfg.seed, event_round=cur_round,
                    revived=req_revive,
                )
            if due_k or due_r or edge_stats["changed"]:
                # the single partition-rule pass, against the final
                # adjacency: unreachable-from-the-majority == failed —
                # stranded survivors and split-off minority components
                # would hang the predicate forever. Re-run after revives
                # too: a returning node counts only once reattached to
                # the majority component. With repair='off' and no churn
                # this is the legacy kill_disconnected(birth_topo, ...)
                # call bitwise (run_topo never leaves the birth CSR).
                alive_host[: topo.num_nodes] = faults_mod.apply_partition_rule(
                    run_topo, alive_host[: topo.num_nodes], cfg.repair
                )
            alive_host[topo.num_nodes:] = False  # padding rows never live
            # nodes that actually (re)joined — revive ids that survived
            # the majority rule — restart from fresh-born state
            reborn = np.flatnonzero(alive_host & ~before)
            if reborn.size:
                from gossipprotocol_tpu.engine.driver import revive_rows

                state = revive_rows(state, reborn, cfg, topo.num_nodes)
            # apply the alive diff on device (scatter), keeping the buffer
            # XLA-owned — a zero-copy device_put of the numpy array would
            # feed externally-owned memory into the donating step
            import jax
            import jax.numpy as jnp

            newly_dead = np.flatnonzero(before & ~alive_host)
            alive_dev = state.alive
            if newly_dead.size:
                alive_dev = alive_dev.at[
                    jnp.asarray(newly_dead, jnp.int32)].set(False)
            if reborn.size:
                alive_dev = alive_dev.at[
                    jnp.asarray(reborn, jnp.int32)].set(True)
            if alive_dev.sharding != state.alive.sharding:
                # the compiled step expects its input layout unchanged
                alive_dev = jax.device_put(alive_dev, state.alive.sharding)
            state = state._replace(alive=alive_dev)

            # one rebuild serves every adjacency change in the batch,
            # under the same conservation assertion repair always had:
            # events must never touch protocol state — push-sum mass over
            # every row is conserved *exactly* across the device rebuild
            new_step = None
            info: dict = {}
            rebuild_s = 0.0
            changed = bool(edge_stats["changed"]
                           or (repair_stats and repair_stats["changed"]))
            if changed:
                if rebuild is None:
                    raise RuntimeError(
                        "topology event fired but the engine supplied no "
                        "rebuild hook"
                    )
                from gossipprotocol_tpu.engine.driver import _mass_snapshot

                mass0 = _mass_snapshot(state)
                t0r = time.perf_counter()
                new_step, state, info = rebuild(run_topo, state)
                rebuild_s = time.perf_counter() - t0r
                mass1 = _mass_snapshot(state)
                # NaN/Inf mass (a prior sentinel-off value fault) makes
                # the equality meaningless — the rebuild is still sound,
                # the state was already poisoned before it
                finite = (mass0 is None
                          or all(np.isfinite(v) for v in mass0))
                if finite and mass0 != mass1:
                    raise AssertionError(
                        f"event rebuild changed protocol mass: "
                        f"{mass0} -> {mass1} (policy={cfg.repair}, "
                        f"round={cur_round})"
                    )

            records = []
            if repair_stats is not None:
                # legacy record shape: when no edge events rode the
                # batch, the rebuild provenance lands here exactly as the
                # pre-engine driver emitted it
                rec = {
                    "event": "repair",
                    "round": cur_round,
                    "policy": cfg.repair,
                    "rebuild_s": 0.0 if due_e else rebuild_s,
                    **{k: v for k, v in repair_stats.items()},
                    **({} if due_e else info),
                }
                records.append(rec)
            if due_e:
                records.append({
                    "event": "churn",
                    "round": cur_round,
                    "generated": generated,
                    "rebuild_s": rebuild_s,
                    **edge_stats,
                    **info,
                })

            # value-fault injection LAST: the corruption must never leak
            # into the rebuild's conservation snapshot, and the sample is
            # filtered by the final alive mask so already-quarantined
            # (dead) rows stay untouched — the property that makes a
            # post-rollback replay of the fault a no-op
            for r in due_v:
                for vf in self.value_faults.pop(r):
                    from gossipprotocol_tpu.engine.driver import (
                        inject_value_fault,
                    )

                    drawn = plan_mod.value_fault_ids(
                        topo.num_nodes, vf, run_seed=cfg.seed)
                    hit = drawn[alive_host[drawn]]
                    if hit.size:
                        state = inject_value_fault(state, hit, vf, cfg,
                                                   topo.num_nodes)
                    records.append({
                        "event": "value_fault",
                        "round": cur_round,
                        "fault_round": int(vf.round),
                        "model": str(vf.model),
                        "rate": float(vf.rate),
                        "drawn": int(drawn.size),
                        "nodes": int(hit.size),
                    })
        return state, run_topo, new_step, records, int(reborn.size)

    def quarantine(self, state, run_topo, cur_round: int, ids, rebuild):
        """Quarantine ``ids`` at ``cur_round``: a synthetic kill through
        the normal pipeline, with one twist — the offending rows' mass is
        zeroed on device FIRST, so the poison (NaN/Inf/adversarial mass)
        leaves the network the instant the nodes do and the rebuild's
        conservation snapshot stays finite.

        Everything due at ``cur_round`` co-fires in the same pipeline
        pass (exactly how the resume replay merges a logged quarantine
        into the scheduled kills of the same round), so live and replayed
        topology sequences stay bitwise-identical. Returns
        ``(state, run_topo, new_step_or_None, records)``.
        """
        from gossipprotocol_tpu.engine.driver import quarantine_rows

        ids = np.sort(np.asarray(ids, np.int64).reshape(-1))
        state = quarantine_rows(state, ids)
        prev = self.kills.get(cur_round)
        self.kills[cur_round] = (
            ids if prev is None
            else np.unique(np.concatenate([prev, ids])))
        state, run_topo, new_step, records, _reborn = self.fire(
            state, run_topo, cur_round, rebuild)
        records.append({
            "event": "quarantine",
            "round": cur_round,
            "nodes": int(ids.size),
            "ids": ids[:64].tolist(),
            "policy": self.cfg.repair,
        })
        return state, run_topo, new_step, records


def replay_topology_events(topo: Topology, schedule, plan, policy: str,
                           run_seed: int, upto_round: int,
                           quarantines=None) -> Topology:
    """Reconstruct the adjacency in force at a resume point.

    A checkpoint at round ``C`` reflects every event with ``r < C`` (the
    engine fires events at the top of the chunk loop and prunes
    strictly-past events on resume). Replaying those rounds in order —
    kills, revives, edge events, repair, partition rule, exactly as
    :meth:`HostEvents.fire` batches them — reproduces the live topology
    sequence bitwise: explicit events are literal, churn and repair key
    their rngs per event round, and the CSR rebuilds are canonical.

    ``quarantines`` maps a round to the node ids the sentinel quarantined
    there (checkpoint ``quarantines`` metadata): dynamic kills a pure
    replay could never re-derive, merged into the scheduled kills of
    their round exactly as :meth:`HostEvents.quarantine` co-fired them.
    """
    from gossipprotocol_tpu.topology import repair as repair_mod
    from gossipprotocol_tpu.utils import faults as faults_mod

    repair_mod.validate_policy(policy)
    plan = plan_mod.as_plan(plan)
    quarantines = {int(r): np.asarray(v, np.int64)
                   for r, v in dict(quarantines or {}).items()}
    if policy == "off" and not plan.has_events and not quarantines:
        return topo
    birth = topo.birth_alive()
    alive = (np.ones(topo.num_nodes, bool) if birth is None
             else np.asarray(birth, bool).copy())
    rounds = set(schedule.kills) | set(schedule.revives)
    rounds |= set(plan.explicit_rounds())
    rounds |= set(quarantines)
    if plan.churn is not None and upto_round > plan.churn.period:
        rounds |= set(range(int(plan.churn.period), int(upto_round),
                            int(plan.churn.period)))
    out = topo
    for r in sorted(rounds):
        if r >= upto_round:
            break
        kills = schedule.kills.get(r)
        qids = quarantines.get(r)
        if qids is not None:
            kills = (qids if kills is None else
                     np.unique(np.concatenate(
                         [np.asarray(kills, np.int64), qids])))
        strikes = kills is not None
        if kills is not None:
            alive[np.asarray(kills, np.int64)] = False
        revs = schedule.revives.get(r)
        strikes |= revs is not None
        revived = (np.asarray(revs, np.int64) if revs is not None
                   else np.empty(0, np.int64))
        alive[revived] = True
        out, estats, _ = _apply_round_edge_events(
            out, plan, r, run_seed=run_seed)
        if policy != "off":
            out, _ = repair_mod.repair_topology(
                out, alive, policy, run_seed=run_seed, event_round=r,
                revived=revived)
        if strikes or estats["changed"]:
            alive = faults_mod.apply_partition_rule(out, alive, policy)
    return out


def replay_topology(topo: Topology, cfg, upto_round: int) -> Topology:
    """Config-level wrapper over :func:`replay_topology_events` — the
    engines' resume entry point."""
    return replay_topology_events(
        topo, cfg.schedule, cfg.events, cfg.repair, cfg.seed, upto_round,
        quarantines=dict(getattr(cfg, "quarantine_log", ()) or ()))
