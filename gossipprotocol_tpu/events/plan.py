"""Declarative topology-schedule plans: churn as a first-class workload.

The fault engine (utils/faults.py) changes *liveness*; the repair engine
(topology/repair.py) changes the adjacency in *response* to liveness.
This module adds the third axis the ROADMAP names — the environment
changing the adjacency *itself*: timed edge additions/removals/swaps
(mobile/P2P overlay churn, time-varying mixing graphs — exactly the
regime SGP's theory is built for, arXiv:1811.10792) plus a seeded
synthetic churn generator for trace-free experiments.

An :class:`EventPlan` is pure data: explicit per-round edge events and an
optional :class:`ChurnSpec` generator. Execution lives in
:mod:`gossipprotocol_tpu.events.engine`, which folds these together with
the fault schedule and repair policy into ONE host-event pipeline at
chunk boundaries.

Determinism contract (the bitwise-replay invariant):

* Explicit events are literal edge lists — trivially replayable.
* Generated churn draws from a counter-based rng keyed on
  ``(run_seed, event_round, _CHURN_STREAM)`` and the *current* adjacency,
  never threaded through the run — so a resume can regenerate the exact
  event sequence from the birth topology plus the plan
  (:func:`gossipprotocol_tpu.events.engine.replay_topology`).
* Application rebuilds through :func:`csr_from_edges`, whose output is
  canonical (sorted, deduped) and therefore independent of the order the
  surviving edge set was assembled in.

The plan's :meth:`EventPlan.digest` is a checkpoint trajectory field
(utils/checkpoint.py): resuming under a different plan would splice two
different topology histories and is refused like any seed mismatch.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, Mapping, Optional, Tuple

import numpy as np

from gossipprotocol_tpu.topology.base import Topology, csr_from_edges

CHURN_MODELS = ("edge", "swap")

# Value-fault corruption models (``scale:K`` carries its factor inline).
VALUE_FAULT_MODELS = ("nan", "inf", "stuck", "scale")

# Domain-separation constant for the churn rng key (arbitrary, fixed
# forever: part of the bitwise-replay contract, like repair's
# _REWIRE_STREAM).
_CHURN_STREAM = 0xC4BA9E

# Domain-separation constant for value-fault node draws (fixed forever,
# same contract as _CHURN_STREAM).
_VALUEFAULT_STREAM = 0xFA017

# Rejection-sampling budget per requested churn edge addition (a nearly
# complete graph must not spin; a short add only costs event size, never
# correctness).
_ADD_DRAWS = 16

_PLAN_KEYS = ("add_edges", "remove_edges", "swap_neighbors", "churn",
              "kill", "revive", "loss", "value_faults")


@dataclasses.dataclass(frozen=True)
class ChurnSpec:
    """Seeded synthetic churn generator (``--churn rate,model[,period]``).

    Every ``period`` rounds (rounds ``period, 2*period, ...``) the
    generator emits one churn event sized by ``rate`` (fraction of the
    current undirected edge count, floor 1):

    * ``edge`` — remove that many uniform-random existing edges and add
      the same number of uniform-random new non-edges (overlay membership
      churn).
    * ``swap`` — degree-preserving double-edge swaps: pick 2k random
      edges, pair them, cross the endpoints (mobility-style rewiring that
      keeps every node's degree).
    """

    rate: float
    model: str
    period: int = 10

    def validate(self) -> "ChurnSpec":
        if not 0.0 < self.rate <= 1.0:
            raise ValueError(
                f"churn rate {self.rate} must be in (0, 1] — it is the "
                "fraction of current edges touched per churn event")
        if self.model not in CHURN_MODELS:
            raise ValueError(
                f"churn model must be one of {CHURN_MODELS}, "
                f"got {self.model!r}")
        if int(self.period) < 1:
            raise ValueError(f"churn period {self.period} must be >= 1")
        return self


@dataclasses.dataclass(frozen=True)
class ValueFaultSpec:
    """One seeded value-fault injection (``--value-faults
    rate,model[,round]``).

    At ``round`` a uniform-random sample of ``rate * n`` live nodes
    (floor 1) has its push-sum numerator ``s`` corrupted:

    * ``nan``     — payload becomes NaN (the classic silent poison);
    * ``inf``     — payload becomes +Inf;
    * ``stuck``   — payload resets to the node's initial value (a
      learner that stopped learning but keeps gossiping);
    * ``scale:K`` — payload multiplied by K (an adversarial or
      miscalibrated contribution).

    Node draws use a counter-based rng keyed on
    ``(run_seed, round, _VALUEFAULT_STREAM)`` over *global* ids, so the
    sample is identical across shard counts and resume replays. Dead
    nodes are skipped at fire time — after a quarantine-and-rollback the
    replayed injection lands on already-dead rows and is a no-op.
    """

    rate: float
    model: str
    round: int = 10

    def validate(self) -> "ValueFaultSpec":
        if not 0.0 < self.rate <= 1.0:
            raise ValueError(
                f"value-fault rate {self.rate} must be in (0, 1] — it is "
                "the fraction of live nodes corrupted per event")
        base = str(self.model).split(":", 1)[0]
        if base not in VALUE_FAULT_MODELS:
            raise ValueError(
                f"value-fault model must be one of {VALUE_FAULT_MODELS} "
                f"(scale as 'scale:K'), got {self.model!r}")
        if base == "scale":
            k = self.scale_factor()
            if not np.isfinite(k) or k == 1.0:
                raise ValueError(
                    f"value-fault scale factor must be finite and != 1, "
                    f"got {self.model!r}")
        elif ":" in str(self.model):
            raise ValueError(
                f"value-fault model {self.model!r} takes no ':' argument")
        if int(self.round) < 1:
            raise ValueError(
                f"value-fault round {self.round} must be >= 1")
        return self

    def scale_factor(self) -> float:
        """The K of ``scale:K`` (ValueError for malformed specs)."""
        parts = str(self.model).split(":", 1)
        if parts[0] != "scale" or len(parts) != 2:
            raise ValueError(f"not a scale model: {self.model!r}")
        try:
            return float(parts[1])
        except ValueError:
            raise ValueError(
                f"value-fault scale factor {parts[1]!r} is not a number")


def value_fault_ids(num_nodes: int, spec: ValueFaultSpec, *,
                    run_seed: int) -> np.ndarray:
    """The global ids ``spec`` corrupts — a pure function of
    ``(num_nodes, spec, run_seed)``, independent of shard count and of
    everything the run did before the event round (the churn PRNG
    discipline)."""
    rng = np.random.default_rng(
        [int(run_seed) & 0xFFFFFFFF, int(spec.round), _VALUEFAULT_STREAM])
    k = min(num_nodes, max(1, int(round(spec.rate * num_nodes))))
    return np.sort(rng.choice(num_nodes, size=k, replace=False)).astype(
        np.int64)


@dataclasses.dataclass(frozen=True)
class EventPlan:
    """Timed edge-level topology events + optional churn generator.

    ``adds``/``removes`` map a round to an ``[k, 2]`` int64 edge array;
    ``swaps`` maps a round to ``[k, 4]`` rows ``(u1, v1, u2, v2)`` — the
    classic double-edge swap: both edges must exist, they are removed and
    replaced by ``(u1, v2)`` and ``(u2, v1)``. Treated as immutable after
    construction.
    """

    adds: Mapping[int, np.ndarray] = dataclasses.field(default_factory=dict)
    removes: Mapping[int, np.ndarray] = dataclasses.field(default_factory=dict)
    swaps: Mapping[int, np.ndarray] = dataclasses.field(default_factory=dict)
    churn: Optional[ChurnSpec] = None
    value_faults: Tuple[ValueFaultSpec, ...] = ()

    # ---- queries -------------------------------------------------------

    @property
    def has_events(self) -> bool:
        return (bool(self.adds) or bool(self.removes) or bool(self.swaps)
                or self.churn is not None or bool(self.value_faults))

    def __bool__(self) -> bool:
        return self.has_events

    def explicit_rounds(self) -> Tuple[int, ...]:
        return tuple(sorted(set(self.adds) | set(self.removes)
                            | set(self.swaps)))

    def next_churn_round(self, after: int) -> Optional[int]:
        """Smallest churn round >= ``after`` (churn fires at positive
        multiples of the period), or None without a generator."""
        if self.churn is None:
            return None
        p = int(self.churn.period)
        return max(p, p * -(-int(after) // p))  # ceil-div, floor at p

    # ---- validation ----------------------------------------------------

    def validate(self, num_nodes: Optional[int] = None) -> "EventPlan":
        for name, events, width in (("add_edges", self.adds, 2),
                                    ("remove_edges", self.removes, 2),
                                    ("swap_neighbors", self.swaps, 4)):
            for r, arr in events.items():
                if int(r) < 0:
                    raise ValueError(f"{name} round {r} is negative")
                a = np.asarray(arr)
                if a.ndim != 2 or a.shape[1] != width or not a.size:
                    raise ValueError(
                        f"{name}@{r}: want a non-empty [k, {width}] int "
                        f"array, got shape {a.shape}")
                if (a < 0).any():
                    raise ValueError(f"{name}@{r}: negative node id")
                if num_nodes is not None and (a >= num_nodes).any():
                    raise ValueError(
                        f"{name}@{r}: node id {int(a.max())} out of range "
                        f"for {num_nodes} nodes")
        if self.churn is not None:
            self.churn.validate()
        for vf in self.value_faults:
            vf.validate()
        return self

    # ---- construction --------------------------------------------------

    @classmethod
    def from_events(
        cls,
        adds: Optional[Mapping[int, object]] = None,
        removes: Optional[Mapping[int, object]] = None,
        swaps: Optional[Mapping[int, object]] = None,
        churn: Optional[ChurnSpec] = None,
        value_faults: Tuple[ValueFaultSpec, ...] = (),
    ) -> "EventPlan":
        norm = lambda ev, w: {  # noqa: E731
            int(r): np.asarray(arr, dtype=np.int64).reshape(-1, w)
            for r, arr in (ev or {}).items()
        }
        return cls(adds=norm(adds, 2), removes=norm(removes, 2),
                   swaps=norm(swaps, 4), churn=churn,
                   value_faults=tuple(value_faults))

    # ---- identity ------------------------------------------------------

    def digest(self) -> str:
        """Stable content hash for checkpoint trajectory metadata.

        ``"none"`` for the empty plan, so event-free resumes keep
        matching event-free checkpoints without wildcarding. The churn
        generator hashes by its *parameters* — the materialized events
        are a pure function of (parameters, run seed, topology history),
        and the seed/topology are trajectory-checked separately."""
        if not self:
            return "none"
        doc = {
            "add": {str(r): np.asarray(v).tolist()
                    for r, v in sorted(self.adds.items())},
            "remove": {str(r): np.asarray(v).tolist()
                       for r, v in sorted(self.removes.items())},
            "swap": {str(r): np.asarray(v).tolist()
                     for r, v in sorted(self.swaps.items())},
            "churn": (None if self.churn is None else
                      [self.churn.rate, self.churn.model,
                       int(self.churn.period)]),
        }
        if self.value_faults:
            # Key present only when non-empty: fault-free plans keep
            # their pre-existing digests byte-for-byte.
            doc["value_faults"] = [[int(v.round), v.rate, str(v.model)]
                                   for v in sorted(self.value_faults,
                                                   key=lambda v: v.round)]
        blob = json.dumps(doc, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def value_fault_digest(self) -> str:
        """Stable hash of just the value-fault portion — its own
        checkpoint trajectory field (``"none"`` when the plan injects
        nothing), so a resume under a different fault plan is refused
        even when the topology-event portion matches."""
        if not self.value_faults:
            return "none"
        doc = [[int(v.round), v.rate, str(v.model)]
               for v in sorted(self.value_faults, key=lambda v: v.round)]
        blob = json.dumps(doc, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def value_fault_rounds(self) -> Tuple[int, ...]:
        return tuple(sorted({int(v.round) for v in self.value_faults}))


_EMPTY_PLAN = EventPlan()


def as_plan(event_plan: Optional[EventPlan]) -> EventPlan:
    """Normalize RunConfig's optional field into one EventPlan (possibly
    empty), so call sites test ``plan.has_events`` instead of None."""
    return event_plan if event_plan is not None else _EMPTY_PLAN


def parse_churn_arg(spec: str) -> ChurnSpec:
    """``--churn RATE,MODEL[,PERIOD]`` -> validated ChurnSpec."""
    parts = [p.strip() for p in str(spec).split(",")]
    if len(parts) not in (2, 3):
        raise ValueError(
            f"--churn wants RATE,MODEL[,PERIOD], got {spec!r} "
            f"(models: {CHURN_MODELS}, period default 10)")
    try:
        rate = float(parts[0])
    except ValueError:
        raise ValueError(f"--churn rate {parts[0]!r} is not a number")
    period = 10
    if len(parts) == 3:
        try:
            period = int(parts[2])
        except ValueError:
            raise ValueError(f"--churn period {parts[2]!r} is not an int")
    return ChurnSpec(rate=rate, model=parts[1], period=period).validate()


def parse_value_faults_arg(spec: str) -> ValueFaultSpec:
    """``--value-faults RATE,MODEL[,ROUND]`` -> validated ValueFaultSpec."""
    parts = [p.strip() for p in str(spec).split(",")]
    if len(parts) not in (2, 3):
        raise ValueError(
            f"--value-faults wants RATE,MODEL[,ROUND], got {spec!r} "
            f"(models: nan, inf, stuck, scale:K; round default 10)")
    try:
        rate = float(parts[0])
    except ValueError:
        raise ValueError(
            f"--value-faults rate {parts[0]!r} is not a number")
    rnd = 10
    if len(parts) == 3:
        try:
            rnd = int(parts[2])
        except ValueError:
            raise ValueError(
                f"--value-faults round {parts[2]!r} is not an int")
    return ValueFaultSpec(rate=rate, model=parts[1], round=rnd).validate()


def parse_event_plan(obj, num_nodes: Optional[int] = None, seed: int = 0):
    """Parse the ``--event-plan`` JSON document.

    One declarative file for the whole topology schedule — edge events,
    the churn generator, AND the fault keys the legacy ``--fault-plan``
    carries (so one document can express kills, revives, loss windows and
    churn together)::

        {
          "add_edges":      [{"round": 40, "edges": [[0, 5], [3, 9]]}],
          "remove_edges":   [{"round": 60, "edges": [[1, 2]]}],
          "swap_neighbors": [{"round": 80,
                              "pairs": [[[0, 1], [2, 3]]]}],
          "churn":          {"rate": 0.02, "model": "edge", "period": 25},
          "value_faults":   [{"round": 12, "rate": 0.05, "model": "nan"}],
          "kill":   [{"round": 10, "ids": [1, 2]}],
          "revive": [{"round": 30, "ids": [1, 2]}],
          "loss":   [{"start": 5, "stop": 25, "prob": 0.2}]
        }

    Returns ``(EventPlan, FaultSchedule)`` — the caller merges the fault
    part into its schedule (legacy flags and the plan compile down to the
    same engine). Raises ValueError on any malformed input (the CLI's
    exit-2 contract).
    """
    from gossipprotocol_tpu.utils import faults

    if isinstance(obj, str):
        with open(obj) as f:
            obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError("event plan must be a JSON object")
    unknown = set(obj) - set(_PLAN_KEYS)
    if unknown:
        raise ValueError(
            f"event plan: unknown key(s) {sorted(unknown)} "
            f"(valid: {', '.join(_PLAN_KEYS)})")

    def edge_events(key):
        out: Dict[int, np.ndarray] = {}
        entries = obj.get(key, ())
        if not isinstance(entries, (list, tuple)):
            raise ValueError(f"{key} must be a list of events")
        for ev in entries:
            if not isinstance(ev, dict) or "round" not in ev:
                raise ValueError(f"{key}: each event needs a 'round'")
            r = int(ev["round"])
            if key == "swap_neighbors":
                if "pairs" not in ev:
                    raise ValueError(f"{key}@{r}: needs 'pairs' "
                                     "([[u1,v1],[u2,v2]] entries)")
                try:
                    arr = np.asarray(ev["pairs"],
                                     dtype=np.int64).reshape(-1, 4)
                except (TypeError, ValueError):
                    raise ValueError(
                        f"{key}@{r}: pairs must be [[[u1,v1],[u2,v2]], ...]")
            else:
                if "edges" not in ev:
                    raise ValueError(f"{key}@{r}: needs 'edges' "
                                     "([[u, v], ...])")
                try:
                    arr = np.asarray(ev["edges"],
                                     dtype=np.int64).reshape(-1, 2)
                except (TypeError, ValueError):
                    raise ValueError(f"{key}@{r}: edges must be "
                                     "[[u, v], ...]")
            if not arr.size:
                raise ValueError(f"{key}@{r}: empty event")
            prev = out.get(r)
            out[r] = arr if prev is None else np.concatenate([prev, arr])
        return out

    value_faults = []
    if "value_faults" in obj:
        entries = obj["value_faults"]
        if not isinstance(entries, (list, tuple)):
            raise ValueError("value_faults must be a list of events")
        for ev in entries:
            if (not isinstance(ev, dict) or "rate" not in ev
                    or "model" not in ev):
                raise ValueError(
                    "value_faults: each event needs 'rate' and 'model' "
                    "(optional 'round')")
            extra = set(ev) - {"rate", "model", "round"}
            if extra:
                raise ValueError(
                    f"value_faults: unknown key(s) {sorted(extra)}")
            value_faults.append(ValueFaultSpec(
                rate=float(ev["rate"]), model=str(ev["model"]),
                round=int(ev.get("round", 10))).validate())

    churn = None
    if "churn" in obj:
        c = obj["churn"]
        if not isinstance(c, dict) or "rate" not in c or "model" not in c:
            raise ValueError(
                "churn must be an object with 'rate' and 'model' "
                "(optional 'period')")
        extra = set(c) - {"rate", "model", "period"}
        if extra:
            raise ValueError(f"churn: unknown key(s) {sorted(extra)}")
        churn = ChurnSpec(rate=float(c["rate"]), model=str(c["model"]),
                          period=int(c.get("period", 10))).validate()

    plan = EventPlan.from_events(
        adds=edge_events("add_edges"),
        removes=edge_events("remove_edges"),
        swaps=edge_events("swap_neighbors"),
        churn=churn,
        value_faults=tuple(value_faults),
    ).validate(num_nodes)
    sched = faults.FaultSchedule.from_json(
        {k: obj[k] for k in ("kill", "revive", "loss") if k in obj},
        num_nodes, seed=seed)
    return plan, sched


# ---------------------------------------------------------------------------
# event generation + application (host-side, chunk-boundary only)


def _undirected_edges(topo: Topology):
    """``(u, v)`` arrays (u < v, one record per undirected edge) plus the
    packed-key set the application pass mutates."""
    n = topo.num_nodes
    offsets = np.asarray(topo.offsets, np.int64)
    indices = np.asarray(topo.indices, np.int64)
    row = np.repeat(np.arange(n, dtype=np.int64), np.diff(offsets))
    und = row < indices
    return row[und], indices[und]


def generate_churn(topo: Topology, spec: ChurnSpec, *, run_seed: int,
                   event_round: int):
    """Materialize one churn event at ``event_round`` from the current
    adjacency — a pure function of its arguments, so live runs and resume
    replays regenerate identical events.

    Returns ``(removes [k,2], adds [k,2], swaps [k,4])`` int64 arrays
    (any may be empty).
    """
    if topo.implicit_full:
        raise ValueError("churn needs an explicit edge list; the implicit "
                         "complete graph has no CSR to rewrite")
    n = topo.num_nodes
    u, v = _undirected_edges(topo)
    num_edges = int(u.size)
    empty2 = np.empty((0, 2), np.int64)
    empty4 = np.empty((0, 4), np.int64)
    if num_edges == 0:
        return empty2, empty2, empty4
    rng = np.random.default_rng(
        [int(run_seed) & 0xFFFFFFFF, int(event_round), _CHURN_STREAM])
    k = max(1, int(round(spec.rate * num_edges)))
    if spec.model == "swap":
        c = min(2 * k, num_edges)
        c -= c % 2
        if c < 2:
            return empty2, empty2, empty4
        idx = rng.choice(num_edges, size=c, replace=False)
        quads = np.stack([u[idx[0::2]], v[idx[0::2]],
                          u[idx[1::2]], v[idx[1::2]]], axis=1)
        return empty2, empty2, quads

    # model == "edge": k removals of existing edges + k additions of
    # fresh non-edges (rejection-sampled with a bounded budget)
    k = min(k, num_edges)
    idx = rng.choice(num_edges, size=k, replace=False)
    removes = np.stack([u[idx], v[idx]], axis=1)
    existing = set((u * n + v).tolist())
    adds: list = []
    for _ in range(k * _ADD_DRAWS):
        if len(adds) >= k:
            break
        a = int(rng.integers(n))
        b = int(rng.integers(n))
        if a == b:
            continue
        key = min(a, b) * n + max(a, b)
        if key in existing:
            continue
        existing.add(key)
        adds.append((a, b))
    adds_arr = (np.asarray(adds, np.int64).reshape(-1, 2)
                if adds else empty2)
    return removes, adds_arr, empty4


def apply_edge_events(topo: Topology, *, removes=None, adds=None,
                      swaps=None):
    """Apply one round's edge events to an explicit-CSR topology.

    Order within the round: removals, then swaps (against the
    post-removal edge set), then additions. Invalid entries are
    *skipped and counted*, never fatal — a remove of an absent edge, an
    add of an existing edge or self-loop, a swap whose source edges are
    missing or whose crossed edges already exist: declarative plans stay
    applicable as the graph evolves under churn around them.

    Returns ``(new_topo, stats)`` with plain-typed stats
    (json-serializable, straight into the metrics stream)::

        {"changed": bool, "edges_added": int, "edges_removed": int,
         "edges_swapped": int, "edges_skipped": int}

    ``new_topo is topo`` when nothing changed (callers skip the device
    rebuild). The rebuilt CSR is canonical (:func:`csr_from_edges`), so
    the result is independent of assembly order — the bitwise-replay
    contract.
    """
    stats = {"changed": False, "edges_added": 0, "edges_removed": 0,
             "edges_swapped": 0, "edges_skipped": 0}
    if topo.implicit_full:
        raise ValueError("edge events need an explicit edge list; the "
                         "implicit complete graph has no CSR to rewrite")
    if topo.asymmetric:
        raise ValueError("edge events are defined on symmetric simple "
                         "graphs; got an asymmetric adjacency")
    n = topo.num_nodes
    u, v = _undirected_edges(topo)
    existing = set((u * n + v).tolist())

    key = lambda a, b: min(a, b) * n + max(a, b)  # noqa: E731
    for a, b in np.asarray(removes if removes is not None else (),
                           np.int64).reshape(-1, 2):
        a, b = int(a), int(b)
        k = key(a, b)
        if a == b or k not in existing:
            stats["edges_skipped"] += 1
            continue
        existing.remove(k)
        stats["edges_removed"] += 1
    for a1, b1, a2, b2 in np.asarray(swaps if swaps is not None else (),
                                     np.int64).reshape(-1, 4):
        a1, b1, a2, b2 = int(a1), int(b1), int(a2), int(b2)
        k1, k2 = key(a1, b1), key(a2, b2)
        n1, n2 = key(a1, b2), key(a2, b1)
        if (k1 == k2 or k1 not in existing or k2 not in existing
                or a1 == b2 or a2 == b1 or n1 == n2
                or n1 in existing or n2 in existing):
            stats["edges_skipped"] += 1
            continue
        existing.remove(k1)
        existing.remove(k2)
        existing.add(n1)
        existing.add(n2)
        stats["edges_swapped"] += 1
    for a, b in np.asarray(adds if adds is not None else (),
                           np.int64).reshape(-1, 2):
        a, b = int(a), int(b)
        k = key(a, b)
        if a == b or k in existing:
            stats["edges_skipped"] += 1
            continue
        existing.add(k)
        stats["edges_added"] += 1

    if not (stats["edges_added"] or stats["edges_removed"]
            or stats["edges_swapped"]):
        return topo, stats
    stats["changed"] = True
    keys = np.fromiter(existing, dtype=np.int64, count=len(existing))
    edges = np.stack([keys // n, keys % n], axis=1)
    return csr_from_edges(n, edges, kind=topo.kind), stats
