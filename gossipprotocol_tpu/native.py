"""ctypes loader for the native graph-construction kernels.

``native/graphgen.cpp`` implements the two host-side hot paths of topology
assembly (canonical CSR build, Barabási–Albert generation) with the same
splitmix64 stream as the numpy fallbacks — same seed, bitwise-identical
graph either way (asserted by tests/test_native.py). The library is
optional: everything works without it, just slower at 10M+ nodes.

Build:  ``make -C native``  (or ``python -m gossipprotocol_tpu.native``).
Disable: ``GOSSIP_TPU_NATIVE=0``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libgraphgen.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("GOSSIP_TPU_NATIVE", "1") == "0":
        return None
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    lib.csr_build.restype = ctypes.c_int64
    lib.csr_build.argtypes = [
        ctypes.c_int64, ctypes.c_int64, i64p, i64p, i64p, i32p,
    ]
    lib.ba_edges.restype = ctypes.c_int64
    lib.ba_edges.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, i64p, i64p,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def build_library(quiet: bool = True) -> str:
    """Compile native/libgraphgen.so in place (requires g++)."""
    global _load_attempted, _lib
    subprocess.run(
        ["make", "-C", _NATIVE_DIR],
        check=True,
        capture_output=quiet,
    )
    _load_attempted = False
    _lib = None
    if _load() is None:
        raise RuntimeError(f"built {_LIB_PATH} but failed to load it")
    return _LIB_PATH


def csr_build(
    num_nodes: int, src: np.ndarray, dst: np.ndarray
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Canonical symmetric CSR from an undirected edge list, or None if the
    native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    e = len(src)
    offsets = np.empty(num_nodes + 1, dtype=np.int64)
    indices = np.empty(max(2 * e, 1), dtype=np.int32)
    nnz = lib.csr_build(num_nodes, e, src, dst, offsets, indices)
    if nnz < 0:
        raise ValueError("csr_build: edge index out of range")
    return offsets, indices[:nnz].copy()


def ba_edges(num_nodes: int, m: int, seed: int) -> Optional[np.ndarray]:
    """Barabási–Albert edge list [E, 2], or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    cap = (m + 1) * m // 2 + max(num_nodes - m - 1, 0) * m
    src = np.empty(cap, dtype=np.int64)
    dst = np.empty(cap, dtype=np.int64)
    ne = lib.ba_edges(num_nodes, m, np.uint64(seed & (2**64 - 1)).item(), src, dst)
    if ne < 0:
        raise ValueError("ba_edges: invalid n/m")
    return np.stack([src[:ne], dst[:ne]], axis=1)


if __name__ == "__main__":
    print(build_library(quiet=False))
    print("native kernels available:", available())
