"""ctypes loader for the native graph-construction kernels.

``native/graphgen.cpp`` implements the two host-side hot paths of topology
assembly (canonical CSR build, Barabási–Albert generation) with the same
splitmix64 stream as the numpy fallbacks — same seed, bitwise-identical
graph either way (asserted by tests/test_native.py). The library is
optional: everything works without it, just slower at 10M+ nodes.

Build:  ``make -C native``  (or ``python -m gossipprotocol_tpu.native``).
Disable: ``GOSSIP_TPU_NATIVE=0``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libgraphgen.so")
_ASYNC_LIB_PATH = os.path.join(_NATIVE_DIR, "libasyncsim.so")
_ROUTE_LIB_PATH = os.path.join(_NATIVE_DIR, "libroutecolor.so")

_I64P = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def _configure_graphgen(lib: ctypes.CDLL) -> None:
    lib.csr_build.restype = ctypes.c_int64
    lib.csr_build.argtypes = [
        ctypes.c_int64, ctypes.c_int64, _I64P, _I64P, _I64P, _I32P,
    ]
    lib.ba_edges.restype = ctypes.c_int64
    lib.ba_edges.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, _I64P, _I64P,
    ]


def _configure_asyncsim(lib: ctypes.CDLL) -> None:
    lib.async_gossip.restype = ctypes.c_int64
    lib.async_gossip.argtypes = [
        ctypes.c_int64, _I64P, _I32P, ctypes.c_uint64, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int64,
    ]
    lib.async_gossip_cost.restype = ctypes.c_int64
    lib.async_gossip_cost.argtypes = [
        ctypes.c_int64, _I64P, _I32P, ctypes.c_uint64, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.async_pushsum_walk.restype = ctypes.c_int64
    lib.async_pushsum_walk.argtypes = [
        ctypes.c_int64, _I64P, _I32P, ctypes.c_uint64, ctypes.c_int64,
        ctypes.c_int64,
    ]


# path -> (loaded-or-None, attempted) — one loading policy for all libs
_libs: dict = {}


def _load_shared(path: str, configure) -> Optional[ctypes.CDLL]:
    if path in _libs:
        return _libs[path]
    if os.environ.get("GOSSIP_TPU_NATIVE", "1") == "0" or not os.path.exists(path):
        _libs[path] = None
        return None
    try:
        lib = ctypes.CDLL(path)
        configure(lib)
    except OSError:
        lib = None
    _libs[path] = lib
    return lib


def _load() -> Optional[ctypes.CDLL]:
    return _load_shared(_LIB_PATH, _configure_graphgen)


def _load_async() -> Optional[ctypes.CDLL]:
    return _load_shared(_ASYNC_LIB_PATH, _configure_asyncsim)


def _configure_routecolor(lib: ctypes.CDLL) -> None:
    lib.route_color_tiles.restype = ctypes.c_int64
    lib.route_color_tiles.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, _I32P, _I32P, _I32P,
    ]
    # fused tile router is absent from pre-round-5 builds of the .so;
    # callers probe hasattr and fall back to the numpy pipeline
    if hasattr(lib, "route_tiles_full"):
        lib.route_tiles_full.restype = ctypes.c_int64
        lib.route_tiles_full.argtypes = [
            ctypes.c_int64, ctypes.c_int32, _I64P,
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
        ]
    # stage-planner kernels are newer still; same probe-and-fallback rule
    if hasattr(lib, "set_native_threads"):
        lib.set_native_threads.restype = None
        lib.set_native_threads.argtypes = [ctypes.c_int32]
    if hasattr(lib, "plan_stage_count"):
        lib.plan_stage_count.restype = ctypes.c_int64
        lib.plan_stage_count.argtypes = [
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            _I64P, _I32P, _I32P, ctypes.POINTER(ctypes.c_int64),
        ]
    if hasattr(lib, "plan_stage_place"):
        lib.plan_stage_place.restype = ctypes.c_int64
        # new_pos/perm passed as raw pointers: perm is optional (NULL
        # skips the permutation fill on geometry-only passes) and
        # ndpointer argtypes reject None
        lib.plan_stage_place.argtypes = [
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            _I64P, _I32P, _I32P, ctypes.c_void_p, ctypes.c_void_p,
        ]


def _load_routecolor() -> Optional[ctypes.CDLL]:
    return _load_shared(_ROUTE_LIB_PATH, _configure_routecolor)


def available() -> bool:
    return _load() is not None


def async_available() -> bool:
    return _load_async() is not None


def routecolor_available() -> bool:
    return _load_routecolor() is not None


def route_color_tiles(
    src_rows: np.ndarray, dst_rows: np.ndarray, n: int, deg: int
) -> Optional[np.ndarray]:
    """Batch Euler-split edge coloring (see ``native/routecolor.cpp``).

    ``src_rows``/``dst_rows``: int32 ``[T, n*deg]`` row ids in ``[0, n)``
    forming, per tile, a ``deg``-regular bipartite multigraph.  Returns a
    proper coloring ``[T, n*deg]`` with colors in ``[0, deg)``, or None
    when the native library is unavailable.
    """
    lib = _load_routecolor()
    if lib is None:
        return None
    src = np.ascontiguousarray(src_rows, dtype=np.int32)
    dst = np.ascontiguousarray(dst_rows, dtype=np.int32)
    tiles = int(np.prod(src.shape[:-1], dtype=np.int64)) if src.ndim > 1 else 1
    color = np.empty_like(src)
    rc = lib.route_color_tiles(
        tiles, n, deg, src.reshape(-1), dst.reshape(-1), color.reshape(-1)
    )
    if rc != 0:
        raise ValueError(f"route_color_tiles: malformed input (rc={rc})")
    return color


def route_tiles_full(perms: np.ndarray, unit: int) -> Optional[np.ndarray]:
    """Fused native tile router (see ``native/routecolor.cpp``).

    ``perms``: int64 ``[T, U]`` per-tile unit permutations, ``-1`` slots
    allowed (completed to bijections internally with the same fill rule
    as ``ops.plan._complete_bijections``). Returns the stacked gather
    triples int8 ``[T, 3, 128, 128]`` in ``ops.clos.route_tile_perms``'s
    convention, or None when the library (or this entry point) is
    unavailable.
    """
    lib = _load_routecolor()
    if lib is None or not hasattr(lib, "route_tiles_full"):
        return None
    perms = np.ascontiguousarray(perms, dtype=np.int64)
    # the C side derives U from unit alone and strides the buffer by it —
    # a mismatched width would read out of bounds, not raise
    if perms.ndim != 2 or perms.shape[1] != 16384 // unit:
        raise ValueError(
            f"route_tiles_full: perms must be [T, {16384 // unit}] for "
            f"unit={unit}, got {perms.shape}")
    t = perms.shape[0]
    idx = np.empty((t, 3, 128, 128), np.int8)
    rc = lib.route_tiles_full(t, unit, perms.reshape(-1), idx.reshape(-1))
    if rc != 0:
        raise ValueError(f"route_tiles_full: non-injective perm (rc={rc})")
    return idx


def set_native_threads(n: int) -> None:
    """Clamp the OpenMP thread count of the native kernels (no-op when
    the library is absent or predates the entry point). Used by the
    shard-build worker pool to split host cores across workers; thread
    count never affects results."""
    lib = _load_routecolor()
    if lib is not None and hasattr(lib, "set_native_threads"):
        lib.set_native_threads(int(n))


def plan_stage_pack(
    pos: np.ndarray, bucket: np.ndarray, u: int, b: int, t_grid: int
) -> Optional[Tuple[np.ndarray, int]]:
    """Counting-sort run packing for one compiler stage (see
    ``native/routecolor.cpp::plan_stage_count``).

    ``pos``: int64 ``[F]`` distinct unit positions < ``t_grid * u``;
    ``bucket``: ``[F]`` radix buckets in ``[0, b)``.  Returns
    ``(rank, max_run)`` — each flow's rank within its (tile, bucket)
    run in ascending-``pos`` order (bitwise the order the numpy stable
    argsort assigns) and the longest run in units — or None when the
    library (or this entry point) is unavailable.
    """
    lib = _load_routecolor()
    if lib is None or not hasattr(lib, "plan_stage_count"):
        return None
    pos = np.ascontiguousarray(pos, dtype=np.int64)
    bucket32 = np.ascontiguousarray(bucket, dtype=np.int32)
    rank = np.empty(pos.size, np.int32)
    max_run = ctypes.c_int64(0)
    rc = lib.plan_stage_count(
        pos.size, t_grid, u, b, pos, bucket32, rank,
        ctypes.byref(max_run))
    if rc != 0:
        raise ValueError(
            f"plan_stage_count: malformed flows (rc={rc}: "
            f"{'duplicate pos' if rc == 2 else 'out of range'})")
    return rank, int(max_run.value)


def plan_stage_place(
    pos: np.ndarray, bucket: np.ndarray, rank: np.ndarray,
    u: int, unit: int, b: int, cr: int, o: int, tau_in: int,
    tau_slab: int, perm: Optional[np.ndarray] = None,
) -> Optional[np.ndarray]:
    """Fused flow placement for one compiler stage (see
    ``native/routecolor.cpp::plan_stage_place``).

    Returns ``new_pos`` int64 ``[F]`` and, when ``perm`` (int64
    ``[t_grid * o, u]`` pre-filled with -1) is given, scatters each
    flow's source unit into it in place.  None when unavailable.
    """
    lib = _load_routecolor()
    if lib is None or not hasattr(lib, "plan_stage_place"):
        return None
    pos = np.ascontiguousarray(pos, dtype=np.int64)
    bucket32 = np.ascontiguousarray(bucket, dtype=np.int32)
    rank32 = np.ascontiguousarray(rank, dtype=np.int32)
    new_pos = np.empty(pos.size, np.int64)
    if perm is not None:
        assert perm.dtype == np.int64 and perm.flags.c_contiguous
    rc = lib.plan_stage_place(
        pos.size, u, unit, b, cr, o, tau_in, tau_slab, pos, bucket32,
        rank32, new_pos.ctypes.data, perm.ctypes.data if perm is not None
        else None)
    if rc != 0:
        raise ValueError(f"plan_stage_place: malformed geometry (rc={rc})")
    return new_pos


def _topo_csr64(topo):
    if topo.implicit_full:
        # materialize K_n for the oracle (small-n cross-validation only)
        n = topo.num_nodes
        if n > 20_000:
            raise ValueError("oracle on implicit full topology: n too large")
        ids = np.arange(n, dtype=np.int32)
        indices = np.ascontiguousarray(
            np.stack([np.delete(ids, i) for i in range(n)]).reshape(-1)
        )
        offsets = np.arange(0, n * (n - 1) + 1, n - 1, dtype=np.int64)
        return offsets, indices
    offsets = np.ascontiguousarray(topo.offsets, dtype=np.int64)
    indices = np.ascontiguousarray(topo.indices, dtype=np.int32)
    return offsets, indices


def async_gossip_events(
    topo, seed: int, threshold: int = 11, start_node: int = 0,
    max_events: int = 100_000_000,
) -> Optional[int]:
    """Message events to global convergence under the reference's *actor*
    semantics (asynchronous oracle; see native/asyncsim.cpp). None if the
    oracle library is unavailable; raises if convergence is not reached
    within max_events."""
    lib = _load_async()
    if lib is None:
        return None
    offsets, indices = _topo_csr64(topo)
    ev = lib.async_gossip(
        topo.num_nodes, offsets, indices, np.uint64(seed & (2**64 - 1)).item(),
        threshold, start_node, max_events,
    )
    if ev < 0:
        raise RuntimeError("async_gossip: no convergence within max_events")
    return int(ev)


def async_gossip_dispatch_cost(
    topo, seed: int, threshold: int = 11, start_node: int = 0,
    max_events: int = 100_000_000, threads: int = 8,
) -> Optional[Tuple[int, int]]:
    """(events, dispatcher_cost) under the reference's actor semantics.

    The cost integrates a virtual dispatcher clock: one oracle sweep is
    one round-robin pass over runnable actors; with ``threads`` worker
    threads it costs ``max(sweep_events, threads)`` thread-time units —
    saturated for fan-out topologies, per-event latency-bound when only
    the rumor frontier is runnable (line gossip). Same RNG stream as
    :func:`async_gossip_events`, so the returned events match it
    exactly. None if the oracle library is unavailable.
    """
    lib = _load_async()
    if lib is None:
        return None
    offsets, indices = _topo_csr64(topo)
    cost = ctypes.c_int64(0)
    ev = lib.async_gossip_cost(
        topo.num_nodes, offsets, indices, np.uint64(seed & (2**64 - 1)).item(),
        threshold, start_node, max_events, threads, ctypes.byref(cost),
    )
    if ev < 0:
        raise RuntimeError("async_gossip_cost: no convergence in max_events")
    return int(ev), int(cost.value)


def async_pushsum_hops(
    topo, seed: int, start_node: int = 0, max_hops: int = 1_000_000_000
) -> Optional[int]:
    """Hops of the reference's single-token push-sum walk until every node
    'converges' on its 2nd receipt (SURVEY.md §2.4.2 — the 2-cover time).
    None if unavailable; raises on non-convergence."""
    lib = _load_async()
    if lib is None:
        return None
    offsets, indices = _topo_csr64(topo)
    hops = lib.async_pushsum_walk(
        topo.num_nodes, offsets, indices, np.uint64(seed & (2**64 - 1)).item(),
        start_node, max_hops,
    )
    if hops < 0:
        raise RuntimeError("async_pushsum_walk: trapped or max_hops reached")
    return int(hops)


def build_library(quiet: bool = True) -> str:
    """Compile the native libraries in place (requires g++)."""
    subprocess.run(
        ["make", "-C", _NATIVE_DIR],
        check=True,
        capture_output=quiet,
    )
    # a pre-build _load() caches None for a missing .so; drop stale entries
    # so the freshly built libraries get probed again
    _libs.pop(_LIB_PATH, None)
    _libs.pop(_ASYNC_LIB_PATH, None)
    _libs.pop(_ROUTE_LIB_PATH, None)
    if _load() is None:
        raise RuntimeError(f"built {_LIB_PATH} but failed to load it")
    return _LIB_PATH


def csr_build(
    num_nodes: int, src: np.ndarray, dst: np.ndarray
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Canonical symmetric CSR from an undirected edge list, or None if the
    native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    src = np.ascontiguousarray(src, dtype=np.int64)
    dst = np.ascontiguousarray(dst, dtype=np.int64)
    e = len(src)
    offsets = np.empty(num_nodes + 1, dtype=np.int64)
    indices = np.empty(max(2 * e, 1), dtype=np.int32)
    nnz = lib.csr_build(num_nodes, e, src, dst, offsets, indices)
    if nnz < 0:
        raise ValueError("csr_build: edge index out of range")
    return offsets, indices[:nnz].copy()


def ba_edges(num_nodes: int, m: int, seed: int) -> Optional[np.ndarray]:
    """Barabási–Albert edge list [E, 2], or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    cap = (m + 1) * m // 2 + max(num_nodes - m - 1, 0) * m
    src = np.empty(cap, dtype=np.int64)
    dst = np.empty(cap, dtype=np.int64)
    ne = lib.ba_edges(num_nodes, m, np.uint64(seed & (2**64 - 1)).item(), src, dst)
    if ne < 0:
        raise ValueError("ba_edges: invalid n/m")
    return np.stack([src[:ne], dst[:ne]], axis=1)


if __name__ == "__main__":
    print(build_library(quiet=False))
    print("native kernels available:", available())
