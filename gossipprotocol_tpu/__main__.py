from gossipprotocol_tpu.cli import main

raise SystemExit(main())
