"""The reference's *actual* push-sum dynamics: a single-token random walk.

Because each ``MainPushSum`` handler sends exactly one message
(``Program.fs:128``), the reference never runs a parallel protocol —
exactly one ``(s, w)`` message exists in the whole system at any time
(SURVEY.md §2.4.2, §3.3). Combined with the commit-before-compare bug
(delta identically zero, ``Program.fs:109-114``) and ``count``
initialized to 1 (``Program.fs:67``), a node "converges" upon receiving
its **2nd** message, so the reference's reported convergence time is the
2-cover time of a random walk.

Rounds 1-4 emulated this with an all-nodes-send round under the broken
predicate and owned the true dynamics in the C++ oracle
(``native/asyncsim.cpp::async_pushsum_walk``). This module renders the
walk **in the engine**: one engine round = one token hop, so
``--semantics reference`` push-sum reproduces the reference end-to-end —
receipt counting, post-convergence relays (``Program.fs:129-131``), the
halve-and-forward mass dynamics — and its ``rounds`` output is directly
a hop count, cross-validated against the oracle's distribution
(tests/test_walk.py).

A serial walk is one scalar update per round — the one protocol here
that a TPU cannot parallelize, because the *reference semantics being
rendered* are serial. It stays worthwhile on-device: the whole chunk of
hops runs inside one ``lax.while_loop`` dispatch, so the host loop and
tunnel round-trips amortize exactly like the parallel protocols'. The
walk is single-chip by nature; the sharded engine rejects it loudly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from gossipprotocol_tpu.protocols.sampling import (
    CSRNeighbors,
    DenseNeighbors,
    InvertedDense,
)


class WalkState(NamedTuple):
    """Per-node arrays mirror ``PushSumState``; three scalars carry the
    token: its position and the in-flight ``(s, w)`` message (a relay
    chain through converged nodes preserves the message unchanged, so it
    cannot be reconstructed from node state)."""

    s: jax.Array           # float[N]  node sum components
    w: jax.Array           # float[N]  node weight components
    ratio: jax.Array       # float[N]  current s/w estimates
    streak: jax.Array      # int32[N]  the reference's ``count`` (starts 1)
    converged: jax.Array   # bool[N]
    alive: jax.Array       # bool[N]
    round: jax.Array       # int32 scalar — hop count
    cur: jax.Array         # int32 scalar — token position
    msg_s: jax.Array       # float scalar — in-flight message
    msg_w: jax.Array       # float scalar


def pushsum_walk_init(
    num_nodes: int,
    start_node: int,
    value_mode: str = "scaled",
    dtype=jnp.float32,
) -> WalkState:
    """Initial walk state, seed message already emitted.

    The driver's seed ``MainPushSum(0.0, 1.0, "start")`` makes the start
    node halve its own pair and send one half (``Program.fs:102-106``) —
    no receipt is counted there, exactly like the oracle's walk starting
    *at* ``start_node`` with its first hop landing on a neighbor.
    ``value_mode`` as in :func:`~gossipprotocol_tpu.protocols.state.
    pushsum_init` (``"index"`` is the reference's ``s_i = i``).
    """
    i = jnp.arange(num_nodes, dtype=dtype)
    s = i / num_nodes if value_mode == "scaled" else i
    w = jnp.ones(num_nodes, dtype)
    s = s.at[start_node].mul(0.5)
    w = w.at[start_node].mul(0.5)
    return WalkState(
        s=s,
        w=w,
        ratio=s / jnp.maximum(w, jnp.asarray(1e-30, dtype)),
        # the reference's ``count`` starts at 1 (Program.fs:67)
        streak=jnp.ones(num_nodes, jnp.int32),
        converged=jnp.zeros(num_nodes, bool),
        alive=jnp.ones(num_nodes, bool),
        round=jnp.int32(0),
        cur=jnp.int32(start_node),
        msg_s=s[start_node],
        msg_w=w[start_node],
    )


def _draw_next(nbrs, n: int, key: jax.Array, cur: jax.Array):
    """(target, movable): one uniform neighbor draw for the token holder.

    The reference draws with a fresh ``Random()`` per message
    (``Program.fs:128,130``); here the draw is counter-based on the hop
    number — deterministic replay, same as every other sampler in
    :mod:`protocols.sampling`. ``movable=False`` means the holder has no
    neighbors (a trapped walk — build_protocol rejects the only config
    that could produce one, an explicitly isolated --seed-node).
    """
    if nbrs is None:  # implicit complete graph: uniform over [0, n) \ {cur}
        t = jax.random.randint(key, (), 0, n - 1)
        t = jnp.where(t >= cur, t + 1, t).astype(jnp.int32)
        return t, jnp.bool_(n > 1)
    if isinstance(nbrs, (DenseNeighbors, InvertedDense)):
        deg = nbrs.degree[cur]
        j = jax.random.randint(key, (), 0, jnp.maximum(deg, 1))
        return nbrs.table[cur, j], deg > 0
    assert isinstance(nbrs, CSRNeighbors)
    deg = nbrs.degree[cur]
    j = jax.random.randint(key, (), 0, jnp.maximum(deg, 1))
    return nbrs.indices[nbrs.starts[cur] + j], deg > 0


@partial(jax.jit, static_argnames=("n", "streak_target"), inline=True)
def pushsum_walk_round(
    state: WalkState,
    nbrs,  # CSRNeighbors | DenseNeighbors | InvertedDense | None
    base_key: jax.Array,
    *,
    n: int,
    streak_target: int = 3,
) -> WalkState:
    """One token hop (= one engine round), ``Program.fs:107-131`` exactly:

    the holder sends to a uniform neighbor; an unconverged receiver
    accumulates, advances ``count`` (the delta it should gate on is
    identically zero — the commit-before-compare bug), converges at
    ``count = streak_target``, halves its pair and forwards one half; a
    converged receiver relays the message untouched.
    """
    key = jax.random.fold_in(base_key, state.round)
    tgt, movable = _draw_next(nbrs, n, key, state.cur)

    relay = state.converged[tgt]
    s_acc = state.s[tgt] + state.msg_s
    w_acc = state.w[tgt] + state.msg_w
    count = state.streak[tgt] + 1
    newly = count >= streak_target
    s_half = s_acc * 0.5
    w_half = w_acc * 0.5

    s = state.s.at[tgt].set(jnp.where(relay, state.s[tgt], s_half))
    w = state.w.at[tgt].set(jnp.where(relay, state.w[tgt], w_half))
    streak = state.streak.at[tgt].set(
        jnp.where(relay, state.streak[tgt], count))
    converged = state.converged.at[tgt].set(relay | newly)
    ratio = state.ratio.at[tgt].set(
        s[tgt] / jnp.maximum(w[tgt], jnp.asarray(1e-30, state.w.dtype)))

    # a trapped token (no neighbors) stays put and changes nothing —
    # unreachable from a default start (the seed lands in the giant
    # component and the walk cannot leave it; build_protocol rejects an
    # explicit isolated --seed-node), guarded anyway so a hand-built
    # state can never emit garbage draws
    def keep(new, old):
        return jnp.where(movable, new, old)

    return WalkState(
        s=keep(s, state.s),
        w=keep(w, state.w),
        ratio=keep(ratio, state.ratio),
        streak=keep(streak, state.streak),
        converged=keep(converged, state.converged),
        alive=state.alive,
        round=state.round + 1,
        cur=keep(tgt, state.cur),
        msg_s=keep(jnp.where(relay, state.msg_s, s_half), state.msg_s),
        msg_w=keep(jnp.where(relay, state.msg_w, w_half), state.msg_w),
    )
