"""Protocol state containers.

The reference scatters per-node mutable state across actor closures
(``rumours``, ``sum``/``weight``, ``checkConverge``, ``count`` —
``Program.fs:66-71``) plus a shared ``Dictionary<IActorRef, bool>``
(``Program.fs:37``). Here the whole system state is a handful of dense
arrays in a NamedTuple — a pytree that flows through ``lax.while_loop``,
shards over a device mesh, and checkpoints as an npz file.

``alive`` supports fault injection (SURVEY.md §5.3): a failed node neither
sends nor receives, and the convergence predicate ignores it.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class GossipState(NamedTuple):
    """Gossip rumor-spreading state (reference: ``rumours`` hit counter +
    converged flag per actor, ``Program.fs:66,70``)."""

    counts: jax.Array      # int32[N]  times each node has heard the rumor
    converged: jax.Array   # bool[N]
    alive: jax.Array       # bool[N]   fault-injection mask (True = healthy)
    round: jax.Array       # int32 scalar


class PushSumState(NamedTuple):
    """Push-sum averaging state (reference: ``sum``/``weight``/``count``,
    ``Program.fs:67-69``). ``ratio`` caches s/w from the previous round so
    the convergence delta is computed *against the pre-update estimate* —
    the reference's intended predicate, minus its commit-before-compare bug
    (``Program.fs:109-114``, SURVEY.md §2.4.2)."""

    s: jax.Array           # float[N]  running sum component
    w: jax.Array           # float[N]  running weight component
    ratio: jax.Array       # float[N]  previous-round s/w estimate
    streak: jax.Array      # int32[N]  consecutive rounds with |Δratio| <= eps
    converged: jax.Array   # bool[N]
    alive: jax.Array       # bool[N]
    round: jax.Array       # int32 scalar


def gossip_init(num_nodes: int, seed_node: int, dtype=jnp.int32) -> GossipState:
    """All-zero state with the rumor seeded at ``seed_node``.

    The reference seeds by sending ``Process1`` to a random node
    (``Program.fs:196``): the seed starts *spreading* with ``rumours = 0``.
    Bulk-synchronously the spreading condition is ``counts >= 1``, so the
    seed starts at 1 (its own knowledge of the rumor counts as the first
    hearing — divergence of at most one hit, documented).
    """
    counts = jnp.zeros(num_nodes, dtype).at[seed_node].set(1)
    return GossipState(
        counts=counts,
        converged=jnp.zeros(num_nodes, bool),
        alive=jnp.ones(num_nodes, bool),
        round=jnp.int32(0),
    )


def pushsum_init(
    num_nodes: int,
    value_mode: str = "scaled",
    dtype=jnp.float32,
    reference_semantics: bool = False,
    real_nodes: int | None = None,
) -> PushSumState:
    """Initial push-sum state.

    value_mode:
      * ``"index"``  — s_i = i, the reference's ``InitialSum x``
        (``Program.fs:77-78,174``); true average = (N-1)/2. Needs float64
        beyond ~2^24 nodes for an honest sum.
      * ``"scaled"`` — s_i = i/N (default): identical convergence dynamics,
        average → (N-1)/(2N) ≈ 0.5, numerically safe in float32 at 10M+
        nodes on TPU (documented divergence; the *capability* is s/w →
        mean of initial values, SURVEY.md §2.4.2).

    ``real_nodes``: the true node count N when ``num_nodes`` includes
    sharding padding rows. The scale divisor and the zero-mass cutoff use
    N, never the padded row count — otherwise a padded mesh would start
    real nodes from different values than single-chip and break the
    bitwise sharding-invariance guarantee (found by fuzzing: a 6-node
    graph on 4 devices pads to 8 rows and s_i = i/8 ≠ i/6). Rows >= N get
    s = 0, w = 0: phantom rows carry no mass.

    ``reference_semantics`` starts the streak counter at 1, mirroring the
    reference's ``count`` initialized to 1 (``Program.fs:67``), which —
    combined with its always-zero delta — makes a node "converge" on its
    2nd received message.
    """
    n = real_nodes if real_nodes is not None else num_nodes
    i = jnp.arange(num_nodes, dtype=dtype)
    s = i / n if value_mode == "scaled" else i
    w = jnp.ones(num_nodes, dtype)
    if num_nodes > n:
        phantom = jnp.arange(num_nodes) >= n
        s = jnp.where(phantom, 0, s)
        w = jnp.where(phantom, 0, w)
    streak0 = 1 if reference_semantics else 0
    return PushSumState(
        s=s,
        w=w,
        # maximum guards the zero-weight phantom rows (0/0 -> NaN)
        ratio=s / jnp.maximum(w, jnp.asarray(1e-30, dtype)),
        streak=jnp.full(num_nodes, streak0, jnp.int32),
        converged=jnp.zeros(num_nodes, bool),
        alive=jnp.ones(num_nodes, bool),
        round=jnp.int32(0),
    )
