"""Protocol state containers.

The reference scatters per-node mutable state across actor closures
(``rumours``, ``sum``/``weight``, ``checkConverge``, ``count`` —
``Program.fs:66-71``) plus a shared ``Dictionary<IActorRef, bool>``
(``Program.fs:37``). Here the whole system state is a handful of dense
arrays in a NamedTuple — a pytree that flows through ``lax.while_loop``,
shards over a device mesh, and checkpoints as an npz file.

``alive`` supports fault injection (SURVEY.md §5.3): a failed node neither
sends nor receives, and the convergence predicate ignores it.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class GossipState(NamedTuple):
    """Gossip rumor-spreading state (reference: ``rumours`` hit counter +
    converged flag per actor, ``Program.fs:66,70``)."""

    counts: jax.Array      # int32[N]  times each node has heard the rumor
    converged: jax.Array   # bool[N]
    alive: jax.Array       # bool[N]   fault-injection mask (True = healthy)
    round: jax.Array       # int32 scalar


class PushSumState(NamedTuple):
    """Push-sum averaging state (reference: ``sum``/``weight``/``count``,
    ``Program.fs:67-69``). ``ratio`` caches s/w from the previous round so
    the convergence delta is computed *against the pre-update estimate* —
    the reference's intended predicate, minus its commit-before-compare bug
    (``Program.fs:109-114``, SURVEY.md §2.4.2).

    With ``payload_dim > 1`` (vector payloads) ``s`` and ``ratio`` are
    ``[N, d]``; ``w`` stays ``[N]`` — one weight per node scales every
    payload dimension, exactly as in Stochastic Gradient Push
    (arXiv:1811.10792). ``payload_dim == 1`` keeps the scalar ``[N]``
    shapes, so the d=1 program is bitwise the pre-vector one."""

    s: jax.Array           # float[N] or float[N, d]  running sum component
    w: jax.Array           # float[N]  running weight component
    ratio: jax.Array       # float[N] or float[N, d]  previous s/w estimate
    streak: jax.Array      # int32[N]  consecutive rounds with |Δratio| <= eps
    converged: jax.Array   # bool[N]
    alive: jax.Array       # bool[N]
    round: jax.Array       # int32 scalar


class SGPState(NamedTuple):
    """Stochastic-Gradient-Push state: push-sum fields plus the mean
    train loss of the de-biased estimates, carried so the convergence
    predicate can demand a loss plateau on top of consensus distance.
    Field order matches :class:`PushSumState` so the generic round cores
    (which use ``state._replace``) and the checkpoint/pad/spec machinery
    work unchanged."""

    s: jax.Array           # float[N, d]  biased parameter numerator x
    w: jax.Array           # float[N]  push-sum weight
    ratio: jax.Array       # float[N, d]  de-biased estimate z = x / w
    streak: jax.Array      # int32[N]
    converged: jax.Array   # bool[N]
    alive: jax.Array       # bool[N]
    round: jax.Array       # int32 scalar
    loss: jax.Array        # float32 scalar  mean train loss over alive nodes


class AccelState(NamedTuple):
    """Two-buffer accelerated push-sum state (Chebyshev semi-iterative /
    EPD, arXiv:2202.10742). ``s_prev``/``w_prev`` hold the previous
    iterate for the affine combination x_{t+1} = a_t·W x_t + (1−a_t)·x_{t−1};
    ``omega`` carries the Chebyshev weight recurrence (unused by EPD)."""

    s: jax.Array           # float[N] or float[N, d]
    w: jax.Array           # float[N]
    ratio: jax.Array       # float[N] or float[N, d]
    streak: jax.Array      # int32[N]
    converged: jax.Array   # bool[N]
    alive: jax.Array       # bool[N]
    round: jax.Array       # int32 scalar
    s_prev: jax.Array      # float[N] or float[N, d]  x_{t-1}
    w_prev: jax.Array      # float[N]  w_{t-1}
    omega: jax.Array       # float scalar  Chebyshev ω_t (0 before round 1)


def gossip_init(num_nodes: int, seed_node: int, dtype=jnp.int32) -> GossipState:
    """All-zero state with the rumor seeded at ``seed_node``.

    The reference seeds by sending ``Process1`` to a random node
    (``Program.fs:196``): the seed starts *spreading* with ``rumours = 0``.
    Bulk-synchronously the spreading condition is ``counts >= 1``, so the
    seed starts at 1 (its own knowledge of the rumor counts as the first
    hearing — divergence of at most one hit, documented).
    """
    counts = jnp.zeros(num_nodes, dtype).at[seed_node].set(1)
    return GossipState(
        counts=counts,
        converged=jnp.zeros(num_nodes, bool),
        alive=jnp.ones(num_nodes, bool),
        round=jnp.int32(0),
    )


def pushsum_payload_values(ids, num_nodes: int, payload_dim: int,
                           value_mode: str, dtype, np_mod):
    """Vector-payload initial values for the given node ids: column ``k``
    holds the scalar init of node ``(i + k) mod N`` — each dimension is a
    rotation of the scalar profile, so every dimension has the same known
    mean but a distinct per-node signal. Shared by device init and
    host-side revive so a revived row is bitwise a fresh-born one.

    ``np_mod`` is ``jax.numpy`` (device init) or ``numpy`` (revive); the
    integer→float cast then divide is IEEE-identical in both.
    """
    idx = (ids[:, None] + np_mod.arange(payload_dim)[None, :]) % num_nodes
    vals = idx.astype(dtype)
    if value_mode == "index":
        return vals
    return vals / np_mod.asarray(num_nodes, dtype)


def pushsum_init(
    num_nodes: int,
    value_mode: str = "scaled",
    dtype=jnp.float32,
    reference_semantics: bool = False,
    real_nodes: int | None = None,
    payload_dim: int = 1,
) -> PushSumState:
    """Initial push-sum state.

    value_mode:
      * ``"index"``  — s_i = i, the reference's ``InitialSum x``
        (``Program.fs:77-78,174``); true average = (N-1)/2. Needs float64
        beyond ~2^24 nodes for an honest sum.
      * ``"scaled"`` — s_i = i/N (default): identical convergence dynamics,
        average → (N-1)/(2N) ≈ 0.5, numerically safe in float32 at 10M+
        nodes on TPU (documented divergence; the *capability* is s/w →
        mean of initial values, SURVEY.md §2.4.2).

    ``real_nodes``: the true node count N when ``num_nodes`` includes
    sharding padding rows. The scale divisor and the zero-mass cutoff use
    N, never the padded row count — otherwise a padded mesh would start
    real nodes from different values than single-chip and break the
    bitwise sharding-invariance guarantee (found by fuzzing: a 6-node
    graph on 4 devices pads to 8 rows and s_i = i/8 ≠ i/6). Rows >= N get
    s = 0, w = 0: phantom rows carry no mass.

    ``reference_semantics`` starts the streak counter at 1, mirroring the
    reference's ``count`` initialized to 1 (``Program.fs:67``), which —
    combined with its always-zero delta — makes a node "converge" on its
    2nd received message.
    """
    n = real_nodes if real_nodes is not None else num_nodes
    if payload_dim == 1:
        # scalar path: byte-for-byte the pre-vector program
        i = jnp.arange(num_nodes, dtype=dtype)
        s = i / n if value_mode == "scaled" else i
        w = jnp.ones(num_nodes, dtype)
        if num_nodes > n:
            phantom = jnp.arange(num_nodes) >= n
            s = jnp.where(phantom, 0, s)
            w = jnp.where(phantom, 0, w)
        # maximum guards the zero-weight phantom rows (0/0 -> NaN)
        ratio = s / jnp.maximum(w, jnp.asarray(1e-30, dtype))
    else:
        s = pushsum_payload_values(
            jnp.arange(num_nodes), n, payload_dim, value_mode, dtype, jnp)
        w = jnp.ones(num_nodes, dtype)
        if num_nodes > n:
            phantom = jnp.arange(num_nodes) >= n
            s = jnp.where(phantom[:, None], 0, s)
            w = jnp.where(phantom, 0, w)
        ratio = s / jnp.maximum(w, jnp.asarray(1e-30, dtype))[:, None]
    streak0 = 1 if reference_semantics else 0
    return PushSumState(
        s=s,
        w=w,
        ratio=ratio,
        streak=jnp.full(num_nodes, streak0, jnp.int32),
        converged=jnp.zeros(num_nodes, bool),
        alive=jnp.ones(num_nodes, bool),
        round=jnp.int32(0),
    )
