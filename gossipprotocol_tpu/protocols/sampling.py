"""Random-neighbor sampling.

The reference draws a neighbor with a fresh time-seeded ``System.Random()``
per message (``Program.fs:86,103,128``), which correlates draws within a
clock tick. Here draws are counter-based and **per node identity**: node
``i``'s draw in round ``r`` is ``randint(fold_in(fold_in(base, r), i))``.
Two consequences the reference could never offer:

* deterministic replay — same seed, same trajectory, bitwise;
* sharding invariance — a node's draw depends on its *global* id, not on
  which device holds it, so a 1-device run and an 8-device ``shard_map``
  run of the same experiment take identical trajectories (the
  single-vs-sharded equivalence tests assert this exactly).

Topology arrays are **runtime arguments** (a :class:`CSRNeighbors` pytree),
not jit-closure constants: baking a 10M-node CSR into the HLO module as a
literal would bloat compiles and defeat donation. ``None`` stands for the
implicit complete graph (sampled, never materialized — the reference's
O(n²) full topology, ``Program.fs:211-216``, is its memory wall,
README.md:4).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from gossipprotocol_tpu.topology.base import Topology


class CSRNeighbors(NamedTuple):
    """Device-side CSR adjacency (a pytree; replicated across the mesh —
    state shards, adjacency is read-only shared structure)."""

    starts: jax.Array   # int[N]   offsets[:-1]
    degree: jax.Array   # int32[N]
    indices: jax.Array  # int32[E]


def device_topology(topo: Topology) -> Optional[CSRNeighbors]:
    """Topology → device arrays; None for the implicit complete graph."""
    if topo.implicit_full:
        return None
    return CSRNeighbors(
        starts=jnp.asarray(topo.offsets[:-1]),
        degree=jnp.asarray(topo.degree, dtype=jnp.int32),
        indices=jnp.asarray(topo.indices, dtype=jnp.int32),
    )


def _per_node_randint(key: jax.Array, gids: jax.Array, maxval: jax.Array) -> jax.Array:
    """One independent draw in [0, maxval_i) per global node id.

    Implemented as a single vectorized threefry hash of the global ids
    under the round key — semantically ``randint(fold_in(key, gid))`` per
    node, but one fused TPU op instead of a vmapped per-element key
    derivation (~20× faster at 1M nodes, measured). The modulo map into
    [0, maxval) carries a bias of maxval/2³² — < 10⁻⁶ for explicit CSR
    degrees, but up to ~2.3×10⁻³ on the *implicit full graph* at the
    10M-node north star, where maxval = n-1. A ~0.2% non-uniformity in
    neighbor choice shifts convergence-round statistics by far less than
    seed-to-seed variance, so it is accepted and documented rather than
    paid for with rejection sampling.
    """
    import jax.extend.random as jexr

    kd = jax.random.key_data(key).astype(jnp.uint32)
    g = gids.astype(jnp.uint32)
    # threefry_2x32 splits its count array in half and hashes element i
    # against element i + len/2, so out[i] would depend on array *layout* —
    # which differs between the full arange and a shard's slice. Feeding
    # [g, g] makes each element pair with itself: out[:L] is a pure
    # function of (key, gid), restoring sharding invariance.
    u = jexr.threefry_2x32(kd, jnp.concatenate([g, g]))[: g.shape[0]]
    mx = jnp.broadcast_to(maxval, gids.shape).astype(jnp.uint32)
    return (u % mx).astype(jnp.int32)


def sample_neighbors(
    nbrs: Optional[CSRNeighbors],
    n: int,
    key: jax.Array,
    gids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One uniform-random neighbor per node.

    Args:
      nbrs: replicated CSR adjacency, or None for the implicit complete
        graph on ``n`` nodes.
      n: global (real, unpadded) node count.
      key: round key; per-node independence comes from folding in gids.
      gids: global node ids to sample for — ``arange(n)`` when omitted
        (single-chip); a device's row slice under ``shard_map``. Ids >= n
        (padding rows) come back invalid.

    Returns ``(targets int32[L], valid bool[L])``; invalid rows (padding,
    isolated nodes) have their target pinned to a safe in-range id and must
    be masked out by the caller.
    """
    if gids is None:
        # single-chip fast path: gids == arange(n), so the row lookups are
        # the arrays themselves — two 1M-row gathers saved per round
        gids = jnp.arange(n, dtype=jnp.int32)
        safe_gids = gids
        real = None  # statically all-real
        deg = None if nbrs is None else nbrs.degree
        starts = None if nbrs is None else nbrs.starts
    else:
        real = gids < n
        safe_gids = jnp.minimum(gids, n - 1)
        deg = None if nbrs is None else nbrs.degree[safe_gids]
        starts = None if nbrs is None else nbrs.starts[safe_gids]

    if nbrs is None:
        # Uniform over [0, n) \ {i}: draw in [0, n-1), shift draws >= i up.
        r = _per_node_randint(key, gids, jnp.int32(n - 1))
        targets = r + (r >= safe_gids).astype(jnp.int32)
        if real is None:
            return targets, jnp.ones(targets.shape, bool)
        return jnp.where(real, targets, 0), real

    slot = _per_node_randint(key, gids, jnp.maximum(deg, 1))
    max_slot = nbrs.indices.shape[0] - 1
    flat = jnp.clip(starts + slot.astype(starts.dtype), 0, max(max_slot, 0))
    targets = nbrs.indices[flat]
    valid = (deg > 0) if real is None else (real & (deg > 0))
    return jnp.where(valid, targets, 0), valid


def make_neighbor_sampler(topo: Topology):
    """Closure convenience (tests / notebooks): ``sample(key) -> (targets,
    valid)`` with the device arrays bound."""
    nbrs = device_topology(topo)
    n = topo.num_nodes

    def sample(key: jax.Array):
        return sample_neighbors(nbrs, n, key)

    return sample
