"""Random-neighbor sampling.

The reference draws a neighbor with a fresh time-seeded ``System.Random()``
per message (``Program.fs:86,103,128``), which correlates draws within a
clock tick. Here draws are counter-based and **per node identity**: node
``i``'s draw in round ``r`` is ``randint(fold_in(fold_in(base, r), i))``.
Two consequences the reference could never offer:

* deterministic replay — same seed, same trajectory, bitwise;
* sharding invariance — a node's draw depends on its *global* id, not on
  which device holds it, so a 1-device run and an 8-device ``shard_map``
  run of the same experiment take identical trajectories (the
  single-vs-sharded equivalence tests assert this exactly).

Topology arrays are **runtime arguments** (a :class:`CSRNeighbors` pytree),
not jit-closure constants: baking a 10M-node CSR into the HLO module as a
literal would bloat compiles and defeat donation. ``None`` stands for the
implicit complete graph (sampled, never materialized — the reference's
O(n²) full topology, ``Program.fs:211-216``, is its memory wall,
README.md:4).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gossipprotocol_tpu.topology.base import Topology


class CSRNeighbors(NamedTuple):
    """Device-side CSR adjacency (a pytree; replicated across the mesh —
    state shards, adjacency is read-only shared structure)."""

    starts: jax.Array   # int[N]   offsets[:-1]
    degree: jax.Array   # int32[N]
    indices: jax.Array  # int32[E]


class InvertedDense(NamedTuple):
    """:class:`DenseNeighbors` plus the gather-inversion tables
    (:func:`gossipprotocol_tpu.protocols.gossip.reverse_slot_table`):
    ``rev[i,k]`` = the slot neighbor ``table[i,k]`` must draw to hit i;
    ``deg_nbr[i,k]`` = that neighbor's degree (int8 — the dense path is
    gated at max degree 32). Row-aligned with the state like the dense
    table, so it shards the same way. Accepted anywhere
    :class:`DenseNeighbors` is."""

    table: jax.Array    # int32[rows, max_degree]
    degree: jax.Array   # int32[rows]
    rev: jax.Array      # int8[rows, max_degree]
    deg_nbr: jax.Array  # int8[rows, max_degree]


class DenseNeighbors(NamedTuple):
    """Padded dense adjacency ``table[i, k]`` = k-th neighbor of row i.

    The fast path for bounded-degree graphs (all four reference topologies;
    Erdős–Rényi): selecting a random neighbor becomes a one-hot
    multiply-reduce over the row — pure vectorized elementwise work the TPU
    streams at HBM bandwidth — instead of a 1-element random gather from
    the CSR pool, which XLA lowers to a serial-ish scatter/gather loop
    (measured 1.6 ms vs 22 ms per round at 1M nodes, >13×). Row k ≥
    degree[i] is padding (zeros), never selected because slots are drawn in
    [0, degree[i]).

    Unlike :class:`CSRNeighbors` (replicated), the dense table **shards
    row-wise with the node state**: rows must correspond 1:1 with the rows
    being sampled (full table single-chip; the device's row block under
    ``shard_map``) — which also divides its memory footprint by the device
    count.
    """

    table: jax.Array    # int32[rows, max_degree]
    degree: jax.Array   # int32[rows]


# Above this max degree the dense table stops paying: one-hot work is
# O(N·max_deg), and power-law hubs would blow the table up. CSR covers the
# heavy tail; every reference topology and ER stay far below the cutoff.
DENSE_MAX_DEGREE = 32


def dense_table(topo: Topology) -> "tuple":
    """Host-side padded [N, max_deg] table + degree from the CSR arrays."""
    import numpy as np

    deg = topo.degree.astype(np.int32)
    maxd = int(deg.max()) if deg.size else 1
    table = np.zeros((topo.num_nodes, max(maxd, 1)), dtype=np.int32)
    # CSR indices are row-major, so the row-wise mask scatters them into
    # the right slots in one shot
    mask = np.arange(table.shape[1])[None, :] < deg[:, None]
    table[mask] = topo.indices
    return table, deg


def use_dense(topo: Topology) -> bool:
    """Engine default: dense table when the max degree is bounded
    (≤ ``DENSE_MAX_DEGREE``) and ``GOSSIP_TPU_DENSE`` doesn't disable it."""
    import os

    return (
        not topo.implicit_full
        and os.environ.get("GOSSIP_TPU_DENSE", "1") != "0"
        and int(topo.degree.max() if topo.degree.size else 0)
        <= DENSE_MAX_DEGREE
    )


def chunked_put(arr, max_bytes: int = 512 * 1024 * 1024):
    """Host array -> device, split into <= max_bytes transfers.

    A single multi-GB device_put through the remote (axon) tunnel can
    exceed the worker watchdog's transaction budget — observed crashing
    the 100M-node run when the ~3 GB inversion tables uploaded in one
    piece (artifacts/gossip_100M.json r3 note). Row-sliced puts keep
    every transaction bounded; one on-device concatenate reassembles
    (transient 2x memory for the largest array).
    """
    a = np.asarray(arr)
    if a.nbytes <= max_bytes:
        return jnp.asarray(a)
    row_bytes = max(int(a.itemsize) * int(np.prod(a.shape[1:], dtype=np.int64)), 1)
    rows = max(1, max_bytes // row_bytes)
    parts = [jax.device_put(a[i: i + rows]) for i in range(0, len(a), rows)]
    return jnp.concatenate(parts, axis=0)


def device_topology(topo: Topology, dense: Optional[bool] = None):
    """Topology → device arrays; None for the implicit complete graph.

    ``dense``: force the dense table (True) or CSR (False); default picks
    dense per :func:`use_dense`.
    """
    if topo.implicit_full:
        return None
    if dense is None:
        dense = use_dense(topo)
    if dense:
        table, deg = dense_table(topo)
        return DenseNeighbors(
            table=chunked_put(table), degree=chunked_put(deg)
        )
    return CSRNeighbors(
        starts=chunked_put(topo.offsets[:-1]),
        degree=chunked_put(topo.degree.astype(np.int32)),
        indices=chunked_put(topo.indices.astype(np.int32)),
    )


def _per_node_randint(key: jax.Array, gids: jax.Array, maxval: jax.Array) -> jax.Array:
    """One independent draw in [0, maxval_i) per global node id.

    Implemented as a single vectorized threefry hash of the global ids
    under the round key — semantically ``randint(fold_in(key, gid))`` per
    node, but one fused TPU op instead of a vmapped per-element key
    derivation (~20× faster at 1M nodes, measured). The modulo map into
    [0, maxval) carries a bias of maxval/2³² — < 10⁻⁶ for explicit CSR
    degrees, but up to ~2.3×10⁻³ on the *implicit full graph* at the
    10M-node north star, where maxval = n-1. A ~0.2% non-uniformity in
    neighbor choice shifts convergence-round statistics by far less than
    seed-to-seed variance, so it is accepted and documented rather than
    paid for with rejection sampling.
    """
    import jax.extend.random as jexr

    kd = jax.random.key_data(key).astype(jnp.uint32)
    g = gids.astype(jnp.uint32)
    # threefry_2x32 splits its count array in half and hashes element i
    # against element i + len/2, so out[i] would depend on array *layout* —
    # which differs between the full arange and a shard's slice. Feeding
    # [g, g] makes each element pair with itself: out[:L] is a pure
    # function of (key, gid), restoring sharding invariance.
    u = jexr.threefry_2x32(kd, jnp.concatenate([g, g]))[: g.shape[0]]
    mx = jnp.broadcast_to(maxval, gids.shape).astype(jnp.uint32)
    return (u % mx).astype(jnp.int32)


# Domain-separation constant folded into the round key before drop draws.
# The drop decision and the target draw are both keyed on (round key, gid);
# without a distinct fold the two hashes would be the *same* u32 stream and
# node i's drop coin would correlate perfectly with its neighbor choice.
LOSS_FOLD = 0x10553


def loss_probability(rnd: jax.Array, windows) -> jax.Array:
    """Active drop probability at round ``rnd`` (f32 scalar, traced).

    ``windows`` is the static ``(start, stop, prob)`` tuple from
    :meth:`FaultSchedule.static_loss_windows`. Overlapping windows compose
    as independent Bernoulli drops: survive = Π (1 - pₖ·activeₖ). Because
    the round number is read from device state, loss windows cost no host
    round-trips and no chunk-boundary stops — the kernel turns itself on
    and off.
    """
    survive = jnp.float32(1.0)
    for start, stop, prob in windows:
        active = (rnd >= jnp.int32(start)) & (rnd < jnp.int32(stop))
        if isinstance(prob, jax.Array):
            # traced entry (sweep lanes): the value is the host-rounded
            # float32 SURVIVE factor 1 - p, passed pre-complemented so
            # the single rounding step matches the static program bitwise
            keep = jnp.asarray(prob, jnp.float32)
        else:
            keep = jnp.float32(1.0 - prob)
        survive = survive * jnp.where(active, keep, 1.0)
    return jnp.float32(1.0) - survive


def drop_mask(
    key: jax.Array,
    prob: jax.Array,
    ids: jax.Array,
    ids2: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-message Bernoulli drop decisions, counter-based like every other
    draw in the engine (see :func:`_per_node_randint`).

    ``ids`` alone keys per-sender drops (fanout-one protocols send one
    message per node); ``ids2`` adds the receiver id for per-edge drops
    (fanout-all diffusion sends one message per directed edge). Both ids
    are *global*, so the mask — hence the trajectory — is identical under
    any sharding, and reproducible for a fixed seed. The caller must pass
    a loss-folded key (``fold_in(round_key, LOSS_FOLD)``).
    """
    import jax.extend.random as jexr

    kd = jax.random.key_data(key).astype(jnp.uint32)
    a = ids.astype(jnp.uint32)
    b = a if ids2 is None else ids2.astype(jnp.uint32)
    # pair (a_i, b_i) via the same [x, y] -> threefry(x_i, y_i) layout
    # trick documented in _per_node_randint
    u = jexr.threefry_2x32(kd, jnp.concatenate([a, b]))[: a.shape[0]]
    # u < prob·2³² drops; exact for prob 0 (never) and monotone in prob
    thresh = (prob.astype(jnp.float32) * jnp.float32(4294967296.0))
    return u.astype(jnp.float32) < thresh


def recomputed_hits(nbrs: InvertedDense, key: jax.Array) -> jax.Array:
    """``hit[i, k]``: does neighbor ``table[i,k]``'s draw land on row i?

    The shared core of both gather-inverted deliveries (gossip hit counts,
    push-sum mass): recompute each neighbor's slot draw — the *same*
    ``_per_node_randint(key, gid, max(deg, 1))`` convention
    :func:`sample_neighbors` uses for the forward draw, which is the whole
    exactness contract — and compare it against ``rev[i,k]``, the slot
    that targets i. Elementwise over the static ``[rows, max_deg]``
    tables; ``k >= degree[i]`` padding slots are masked off.
    """
    table = nbrs.table
    rows, maxd = table.shape
    slot = _per_node_randint(
        key, table.reshape(-1),
        jnp.maximum(nbrs.deg_nbr.reshape(-1), 1).astype(jnp.uint32),
    ).reshape(rows, maxd)
    return (slot == nbrs.rev.astype(jnp.int32)) & (
        jnp.arange(maxd, dtype=jnp.int32)[None, :] < nbrs.degree[:, None]
    )


def sample_neighbors(
    nbrs,
    n: int,
    key: jax.Array,
    gids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One uniform-random neighbor per node.

    Args:
      nbrs: adjacency — replicated :class:`CSRNeighbors`, row-aligned
        :class:`DenseNeighbors`, or None for the implicit complete graph
        on ``n`` nodes.
      n: global (real, unpadded) node count.
      key: round key; per-node independence comes from folding in gids.
      gids: global node ids to sample for — ``arange(n)`` when omitted
        (single-chip); a device's row slice under ``shard_map``. Ids >= n
        (padding rows) come back invalid.

    Returns ``(targets int32[L], valid bool[L])``; invalid rows (padding,
    isolated nodes) have their target pinned to a safe in-range id and must
    be masked out by the caller.

    Draws are keyed on *global* ids in every branch, so all backends
    (CSR / dense / implicit-full) and all layouts (single-chip / sharded)
    take bitwise-identical trajectories.
    """
    if isinstance(nbrs, (DenseNeighbors, InvertedDense)):
        # rows of the table correspond 1:1 with the sampled rows by
        # contract (full table, or the local shard under shard_map)
        if gids is None:
            gids = jnp.arange(n, dtype=jnp.int32)
            real = None
        else:
            real = gids < n
        deg = nbrs.degree
        slot = _per_node_randint(key, gids, jnp.maximum(deg, 1))
        # one-hot select of table[i, slot_i]: elementwise + row-reduce
        # (exactly one nonzero per row), no gather — the TPU fast path
        cols = jnp.arange(nbrs.table.shape[1], dtype=slot.dtype)
        onehot = cols[None, :] == slot[:, None]
        targets = jnp.sum(jnp.where(onehot, nbrs.table, 0), axis=1)
        valid = (deg > 0) if real is None else (real & (deg > 0))
        return jnp.where(valid, targets, 0), valid

    if gids is None:
        # single-chip fast path: gids == arange(n), so the row lookups are
        # the arrays themselves — two 1M-row gathers saved per round
        gids = jnp.arange(n, dtype=jnp.int32)
        safe_gids = gids
        real = None  # statically all-real
        deg = None if nbrs is None else nbrs.degree
        starts = None if nbrs is None else nbrs.starts
    else:
        real = gids < n
        safe_gids = jnp.minimum(gids, n - 1)
        deg = None if nbrs is None else nbrs.degree[safe_gids]
        starts = None if nbrs is None else nbrs.starts[safe_gids]

    if nbrs is None:
        # Uniform over [0, n) \ {i}: draw in [0, n-1), shift draws >= i up.
        r = _per_node_randint(key, gids, jnp.int32(n - 1))
        targets = r + (r >= safe_gids).astype(jnp.int32)
        if real is None:
            return targets, jnp.ones(targets.shape, bool)
        return jnp.where(real, targets, 0), real

    slot = _per_node_randint(key, gids, jnp.maximum(deg, 1))
    max_slot = nbrs.indices.shape[0] - 1
    flat = jnp.clip(starts + slot.astype(starts.dtype), 0, max(max_slot, 0))
    targets = nbrs.indices[flat]
    valid = (deg > 0) if real is None else (real & (deg > 0))
    return jnp.where(valid, targets, 0), valid


def send_valid_mask(nbrs, n: int, gids: Optional[jax.Array] = None):
    """Which local rows *can* emit a message (degree > 0 and a real id).

    The telemetry counter functions (obs/counters.py) share this with no
    other purpose: it restates :func:`sample_neighbors`'s ``valid`` output
    without materializing targets, for branches that only need the count.
    Returns None for the single-chip implicit complete graph, where every
    row is statically valid (callers use the row count directly).
    """
    if isinstance(nbrs, (DenseNeighbors, InvertedDense)):
        valid = nbrs.degree > 0
        return valid if gids is None else (valid & (gids < n))
    if nbrs is None:
        return None if gids is None else (gids < n)
    # CSRNeighbors: degree is global-length and replicated
    if gids is None:
        return nbrs.degree > 0
    safe = jnp.minimum(gids, n - 1)
    return (gids < n) & (nbrs.degree[safe] > 0)


def make_neighbor_sampler(topo: Topology):
    """Closure convenience (tests / notebooks): ``sample(key) -> (targets,
    valid)`` with the device arrays bound."""
    nbrs = device_topology(topo)
    n = topo.num_nodes

    def sample(key: jax.Array):
        return sample_neighbors(nbrs, n, key)

    return sample
