"""Bulk-synchronous push-sum distributed averaging.

Reference semantics (``MainPushSum`` handler, ``Program.fs:101-131``): a
node accumulates an incoming ``(s, w)`` pair, checks how much its estimate
``s/w`` moved, halves its pair and forwards one half to a random neighbor;
after converging it relays incoming pairs unchanged. Because each handler
emits exactly one message, the reference degenerates into a single-token
random walk (SURVEY.md §2.4.2), and its convergence test is broken: state
is committed *before* the delta is computed (``Program.fs:109-114``), so
the delta is always zero and a node "converges" on its 2nd message.

This module implements the *intended* protocol — the capability the
reference claims: every round, **every** node halves its ``(s, w)``, keeps
one half, and scatter-adds the other half to one uniform-random neighbor.
Mass is conserved exactly (Σs, Σw invariant — a property the reference
could never test), and per-node estimates ``s/w`` converge to the mean of
the initial values. The convergence predicate is the reference's intended
one: ``|Δ(s/w)| <= eps`` for ``streak_target`` consecutive rounds
(``Program.fs:116-123`` minus the commit-before-compare bug). Converged
nodes keep participating — the bulk-synchronous analogue of the
reference's post-convergence relay (``Program.fs:129-131``) — so the
protocol keeps mixing until the supervisor stops the world.

``reference_semantics=True`` reproduces the reference's accidental
predicate (delta treated as always-zero: the streak increments on every
round with incoming mass, and the counter starts at 1) for curve-matching
against the F# baseline.

Fault injection: a dead node neither sends nor receives; a sender whose
drawn target is dead keeps its half (sender-side aliveness check, the
analogue of ``Program.fs:87``'s dict lookup) — mass stays conserved among
healthy nodes.
"""

from __future__ import annotations

from functools import partial
import jax
import jax.numpy as jnp

from gossipprotocol_tpu.protocols.sampling import (
    LOSS_FOLD,
    device_topology,
    drop_mask,
    loss_probability,
    sample_neighbors,
)
from gossipprotocol_tpu.protocols.state import PushSumState
from gossipprotocol_tpu.topology.base import Topology


def sum0(x: jax.Array) -> jax.Array:
    """Sum over the node axis only: scalar for ``[n]`` state (identical
    program to ``jnp.sum``), per-dimension ``[d]`` for ``[n, d]`` payloads.
    The default ``all_sum`` everywhere, so global means / mass totals are
    per-dimension under vector payloads without touching the d=1 jaxpr."""
    return jnp.sum(x, axis=0)


def rowmask(mask: jax.Array, like: jax.Array) -> jax.Array:
    """Broadcast a per-node ``[rows]`` mask against ``like`` (``[rows]``
    or ``[rows, d]``). The d=1 branch returns ``mask`` itself, keeping the
    scalar-path expressions literally unchanged."""
    return mask if like.ndim == 1 else mask[:, None]


def pushsum_round_core(
    state: PushSumState,
    nbrs,  # CSRNeighbors | DenseNeighbors | None (implicit full graph)
    base_key: jax.Array,
    *,
    n: int,
    gids,
    scatter,
    alive_global: jax.Array,
    eps: float = 1e-10,
    streak_target: int = 3,
    reference_semantics: bool = False,
    predicate: str = "delta",
    tol: float = 1e-4,
    all_sum=sum0,
    all_alive: bool = False,
    targets_alive: bool = False,
    delivery: str = "scatter",
    loss_windows: tuple = (),
    clock: tuple = (),
) -> PushSumState:
    """One synchronous round over the rows in ``gids``.

    Two static fast-path flags:

    * ``all_alive=True`` compiles out every aliveness check — legal only
      when no node can ever be dead (no fault plan, no birth exclusions,
      no padding rows).
    * ``targets_alive=True`` elides only the target-liveness lookup
      ``alive_global[targets]`` — legal whenever the dead set is
      *component-closed* (every alive node's neighbors are alive), which
      holds for birth exclusions (whole components) as long as no fault
      plan can later kill arbitrary nodes. The lookup is a full-length
      random gather, measured ~90 ms/round at 10M nodes (~29 % of the
      round), so this matters for the Erdős–Rényi north star where
      isolated nodes make ``all_alive`` unattainable.

    ``scatter`` is injected (see ``gossip_round_core``); ``alive_global``
    is the full aliveness mask — push-sum needs the *target's* liveness at
    the sender (a dead target's half stays with the sender so mass is
    conserved), and under ``shard_map`` that is an all-gathered copy, taken
    once per chunk since faults only strike between chunks.

    ``predicate`` selects the convergence rule:

    * ``"delta"`` (default) — the reference's *intended* local rule:
      |Δ(s/w)| <= eps for ``streak_target`` consecutive rounds
      (``Program.fs:116-123``). Famously unsound on slow-mixing
      topologies: on a line graph per-round drift falls below any eps
      long before the estimates reach the mean (measured: err ≈ 0.45 at
      n=200 even in float64).
    * ``"global"`` — a sound rule only a bulk-synchronous engine can
      offer: because mass is conserved, the true achievable mean
      Σ(s·alive)/Σ(w·alive) is computable every round (one reduction; a
      ``psum`` under shard_map via ``all_sum``), and a node converges
      when |s/w − mean| <= tol for ``streak_target`` rounds.

    ``clock`` is the static activation-clock spec
    (:mod:`gossipprotocol_tpu.async_`): empty for the synchronous clock
    (this function's body traces byte-identically to the pre-async
    engine), ``(rate, id_div)`` for Poisson clocks, where only rows whose
    clock ticked this round send — an inactive sender keeps its whole
    ``(s, w)``, so mass conservation and both predicates are untouched.
    """
    key = jax.random.fold_in(base_key, state.round)

    if delivery == "invert":
        assert not clock, "delivery='invert' requires the synchronous clock"
        # receiver-side gather delivery (see received_by_inversion): no
        # targets are materialized at all. Build-time validation pinned
        # the legality window: dense table, component-closed dead set,
        # single-chip rows (gids is None), no loss windows (a dropped
        # send must return mass to the sender, which the gather can't).
        assert gids is None, "delivery='invert' is single-chip only"
        assert not loss_windows, "delivery='invert' cannot model loss"
        assert state.s.ndim == 1, "delivery='invert' is scalar-payload only"
        valid = nbrs.degree > 0
        deliver = valid if all_alive else (valid & state.alive)
        s_sent = jnp.where(deliver, state.s * 0.5, jnp.zeros_like(state.s))
        w_sent = jnp.where(deliver, state.w * 0.5, jnp.zeros_like(state.w))
        in_s, in_w = received_by_inversion(nbrs, key, state.s, state.w)
        if not all_alive:
            # dead rows neighbor only dead rows (component closure), but
            # their own gather output is garbage — pin them unchanged
            zero = jnp.zeros_like(in_s)
            in_s = jnp.where(state.alive, in_s, zero)
            in_w = jnp.where(state.alive, in_w, zero)
    else:
        targets, valid = sample_neighbors(nbrs, n, key, gids)

        if all_alive:
            deliver = valid
        elif targets_alive:
            deliver = valid & state.alive
        else:
            deliver = valid & state.alive & alive_global[targets]
        if clock:
            # Poisson activation: a row whose clock did not tick keeps
            # its whole pair this round — mechanically identical to a
            # dead target, so mass stays conserved
            from gossipprotocol_tpu.async_.clock import activation_mask

            gid_rows = (
                gids if gids is not None
                else jnp.arange(state.s.shape[0], dtype=jnp.int32)
            )
            deliver = deliver & activation_mask(key, clock, gid_rows)
        if loss_windows:
            # a dropped send keeps its (s, w) half at the sender — same
            # mechanics as a dead target, so Σs/Σw is conserved and the
            # global predicate / estimate_error stay meaningful
            gid_rows = (
                gids if gids is not None
                else jnp.arange(state.s.shape[0], dtype=jnp.int32)
            )
            p = loss_probability(state.round, loss_windows)
            drop = drop_mask(
                jax.random.fold_in(key, LOSS_FOLD), p, gid_rows
            )
            deliver = deliver & ~drop
        s_sent = jnp.where(
            rowmask(deliver, state.s), state.s * 0.5, jnp.zeros_like(state.s))
        w_sent = jnp.where(deliver, state.w * 0.5, jnp.zeros_like(state.w))

        in_s, in_w = scatter(s_sent, w_sent, targets)

    s_new = state.s - s_sent + in_s
    w_new = state.w - w_sent + in_w
    return finish_pushsum_round(
        state, s_new, w_new, received=in_w > 0,
        eps=eps, streak_target=streak_target,
        reference_semantics=reference_semantics,
        predicate=predicate, tol=tol, all_sum=all_sum, all_alive=all_alive,
    )


def received_by_inversion(nbrs, key: jax.Array, s: jax.Array, w: jax.Array):
    """Receiver-side ``(in_s, in_w)`` — no scatter, one static-index gather.

    The push-sum analogue of gossip's :func:`~gossipprotocol_tpu.protocols.
    gossip.hits_by_inversion`: the counter-based PRNG lets receiver ``i``
    recompute each neighbor's draw, so the mass that lands on it is

        in_s_i = Σ_k [ slot(table[i,k]) == rev[i,k] ] · s[table[i,k]] / 2

    (``w`` alike). Unlike the gossip histogram no value-free shortcut
    exists — ``(s, w)`` must move from sender rows to receiver rows — but
    the movement becomes a **static-index** gather over the dense table
    (stacked ``[rows, max_deg, 2]``, one pass for both streams) plus
    elementwise compare/reduce, instead of two uniform-random
    ``segment_sum`` scatter-adds. The bet was that gathers (no write
    conflicts) beat random scatters.

    Measured outcome (TPU v5e, 1M Erdős–Rényi): the bet LOSES 9x —
    137.7 vs 15.1 ms/round. The draw recompute costs 3.9 ms (the part
    that made gossip's inversion win), but XLA lowers the random-index
    value gather to ~135 ms (two flat gathers: 2.6x worse still): on
    this hardware a random gather costs what a random scatter does, so
    inversion only pays when no sender values are read at all (gossip's
    hit counts). Kept as a validated negative result;
    ``delivery="scatter"`` is the default (README "Performance").

    Exactness contract: reproduces the scatter delivery's multiset of
    messages iff every sender with a valid draw delivers — the engine's
    ``all_alive`` / ``targets_alive`` regimes (every neighbor of a row in
    the table is alive by component-closure; a neighbor's degree is ≥ 1 by
    edge symmetry). The float *summation order* differs from
    ``segment_sum``'s, so trajectories agree to accumulation order, not
    bitwise — delivery choice is therefore an explicit config
    (``RunConfig.delivery``), never an on-device auto-switch like
    gossip's (whose int histograms are bitwise-equal either way).

    ``nbrs`` must be an :class:`~gossipprotocol_tpu.protocols.sampling.
    InvertedDense`; rows beyond the caller's shard are its own concern —
    this helper is single-chip (``table`` holds global ids, and gathering
    ``s`` at them assumes the full state vector is local).
    """
    from gossipprotocol_tpu.protocols.sampling import recomputed_hits

    hit = recomputed_hits(nbrs, key)
    sv = jnp.stack([s, w], axis=-1)          # [n, 2]
    gathered = sv[nbrs.table]                # [rows, maxd, 2] static gather
    zero = jnp.asarray(0, s.dtype)
    in_ = jnp.sum(jnp.where(hit[..., None], gathered, zero), axis=1) * 0.5
    return in_[..., 0], in_[..., 1]


def finish_pushsum_round(
    state: PushSumState,
    s_new,
    w_new,
    received,
    *,
    eps: float,
    streak_target: int,
    reference_semantics: bool,
    predicate: str,
    tol: float,
    all_sum,
    all_alive: bool,
) -> PushSumState:
    """Shared round tail: estimate refresh + convergence predicate.

    Used by both senders — the single-target random-walk round above and
    the fanout-all diffusion round (:mod:`protocols.diffusion`) — so the
    predicate semantics cannot drift between the two.

    Payload-polymorphic: ``s_new`` may be ``[n]`` or ``[n, d]`` (``w`` is
    always per-node). Under vector payloads the per-node predicate
    requires *every* dimension within tolerance, and the new state is
    built with ``state._replace`` so richer state types (SGP, accel) flow
    through with their extra fields intact.
    """
    # The maximum guards dead/isolated rows AND alive nodes in deep
    # receipt dry spells: (s, w) halve every send-only round, so a
    # ~150-round gap drives float32 w through the subnormals to exactly
    # 0 (the measured 100M-scale wall — README "Convergence-predicate
    # soundness"; chunk stats count these as w_underflow). Removing the
    # guard would turn those rows into 0/0 NaNs.
    w_floor = jnp.maximum(w_new, jnp.asarray(1e-30, w_new.dtype))
    ratio_new = s_new / (w_floor if s_new.ndim == 1 else w_floor[:, None])

    if reference_semantics:
        # Program.fs:109-114: delta is computed after the commit and is
        # identically zero, so the counter advances on every received
        # message (here: every round with incoming mass).
        streak = jnp.where(received, state.streak + 1, state.streak)
    elif predicate == "global":
        s_healthy = s_new if all_alive else jnp.where(
            rowmask(state.alive, s_new), s_new, 0)
        w_healthy = w_new if all_alive else jnp.where(state.alive, w_new, 0)
        mean = all_sum(s_healthy) / jnp.maximum(
            all_sum(w_healthy), jnp.asarray(1e-30, w_new.dtype)
        )
        near = jnp.abs(ratio_new - mean) <= tol
        if near.ndim == 2:
            near = jnp.all(near, axis=-1)
        streak = jnp.where(near, state.streak + 1, 0)
    else:
        delta = jnp.abs(ratio_new - state.ratio)
        near = delta <= eps
        if near.ndim == 2:
            near = jnp.all(near, axis=-1)
        streak = jnp.where(near, state.streak + 1, 0)

    if predicate == "global" and not reference_semantics:
        # non-sticky: a node that drifts back out of tol (transient
        # overshoot while mixing continues) un-converges, so the run ends
        # only when every node is simultaneously within tol — the
        # guarantee estimate_error is checked against
        converged = streak >= streak_target
    else:
        # sticky, like the reference's one-shot Alert (Program.fs:94)
        converged = state.converged | (streak >= streak_target)
    return state._replace(
        s=s_new,
        w=w_new,
        ratio=ratio_new,
        streak=streak,
        converged=converged,
        round=state.round + 1,
    )


@partial(
    jax.jit,
    static_argnames=(
        "n", "eps", "streak_target", "reference_semantics", "predicate",
        "tol", "all_alive", "targets_alive", "delivery", "loss_windows",
        "clock",
    ),
    inline=True,
)
def pushsum_round(
    state: PushSumState,
    nbrs,  # CSRNeighbors | DenseNeighbors | InvertedDense | None (implicit full)
    base_key: jax.Array,
    *,
    n: int,
    eps: float = 1e-10,
    streak_target: int = 3,
    reference_semantics: bool = False,
    predicate: str = "delta",
    tol: float = 1e-4,
    all_alive: bool = False,
    targets_alive: bool = False,
    delivery: str = "scatter",
    loss_windows: tuple = (),
    clock: tuple = (),
) -> PushSumState:
    """Single-chip round. ``nbrs``/``base_key`` are runtime arguments so one
    compiled executable serves every same-shape topology and seed."""

    def scatter(s_sent, w_sent, targets):
        return (
            jax.ops.segment_sum(s_sent, targets, num_segments=n),
            jax.ops.segment_sum(w_sent, targets, num_segments=n),
        )

    return pushsum_round_core(
        state,
        nbrs,
        base_key,
        n=n,
        gids=None,
        scatter=scatter,
        alive_global=state.alive,
        eps=eps,
        streak_target=streak_target,
        reference_semantics=reference_semantics,
        predicate=predicate,
        tol=tol,
        all_alive=all_alive,
        targets_alive=targets_alive,
        delivery=delivery,
        loss_windows=loss_windows,
        clock=clock,
    )


def make_pushsum_round(
    topo: Topology,
    base_key: jax.Array,
    eps: float = 1e-10,
    streak_target: int = 3,
    reference_semantics: bool = False,
):
    """Closure convenience: bind topology/key, return ``state -> state``."""
    nbrs = device_topology(topo)
    n = topo.num_nodes

    def round_fn(state: PushSumState) -> PushSumState:
        return pushsum_round(
            state,
            nbrs,
            base_key,
            n=n,
            eps=eps,
            streak_target=streak_target,
            reference_semantics=reference_semantics,
        )

    return round_fn


def pushsum_message_counts(
    old: PushSumState,
    nbrs,
    base_key: jax.Array,
    *,
    n: int,
    gids,
    all_alive: bool,
    targets_alive: bool,
    delivery: str,
    loss_windows: tuple,
    alive_global: jax.Array,
    clock: tuple = (),
) -> jax.Array:
    """Telemetry recount of one single-target push-sum round: int32
    [sent, delivered, dropped] over the local rows (obs/counters.py).

    Re-derives the round's draws through the same
    :func:`~gossipprotocol_tpu.protocols.sampling.sample_neighbors` /
    ``drop_mask`` calls :func:`pushsum_round_core` made — purely
    read-only, so the state trajectory is untouched. ``sent`` counts live
    senders with a valid draw; a half kept because the target was dead
    is sent-not-delivered, one lost to a loss window is ``dropped`` (the
    sender kept the mass either way — drops are mass-conserving).
    """
    key = jax.random.fold_in(base_key, old.round)

    if delivery == "invert":
        # invert is legal only while every send lands (no faults, no
        # loss): sent == delivered by construction
        from gossipprotocol_tpu.protocols.sampling import send_valid_mask

        valid = send_valid_mask(nbrs, n, gids)
        deliver = valid if all_alive else (valid & old.alive)
        cnt = jnp.sum(deliver.astype(jnp.int32))
        return jnp.stack([cnt, cnt, jnp.int32(0)])

    targets, valid = sample_neighbors(nbrs, n, key, gids)
    senders = valid if all_alive else (valid & old.alive)
    if clock:
        # inactive rows sent nothing at all this round
        from gossipprotocol_tpu.async_.clock import activation_mask

        gid_rows_c = (
            gids if gids is not None
            else jnp.arange(old.s.shape[0], dtype=jnp.int32)
        )
        senders = senders & activation_mask(key, clock, gid_rows_c)
    sent = jnp.sum(senders.astype(jnp.int32))
    if all_alive or targets_alive:
        deliver = senders
    else:
        deliver = senders & alive_global[targets]
    if loss_windows:
        gid_rows = (
            gids if gids is not None
            else jnp.arange(old.s.shape[0], dtype=jnp.int32)
        )
        p = loss_probability(old.round, loss_windows)
        drop = drop_mask(jax.random.fold_in(key, LOSS_FOLD), p, gid_rows)
        dropped = jnp.sum((deliver & drop).astype(jnp.int32))
        deliver = deliver & ~drop
    else:
        dropped = jnp.int32(0)
    delivered = jnp.sum(deliver.astype(jnp.int32))
    return jnp.stack([sent, delivered, dropped])


def pushsum_done(state: PushSumState) -> jax.Array:
    """Supervisor predicate: every healthy node's estimate has stabilized."""
    return jnp.all(state.converged | ~state.alive)


def mass(state: PushSumState):
    """(Σs, Σw) — the conservation invariant tests assert on every round."""
    return state.s.sum(), state.w.sum()


def pushsum_trace_row(state, *, all_sum=sum0, all_max=jnp.max) -> jax.Array:
    """Observatory trace row for any push-sum-family state (plain, accel,
    walk, SGP — everything carrying ``s/w/ratio``); see
    :mod:`gossipprotocol_tpu.obs.trace` for the column contract.

    Reads the post-round state only, so the trajectory is untouched.
    ``all_sum`` / ``all_max`` are the cross-shard reductions (node-axis
    sum preserving payload dims, full max) — psum/pmax closures under
    ``shard_map``, so every component of the row is replicated.
    """
    dt = jnp.float32
    alive = state.alive
    live = rowmask(alive, state.ratio)
    # consensus residual against the alive-mass mean (dead rows' stranded
    # mass is excluded, mirroring RunResult.estimate_error)
    sw = all_sum(jnp.where(alive, state.w, 0))
    ss = all_sum(jnp.where(live, state.s, 0))
    mean = ss / jnp.maximum(sw, jnp.asarray(1e-30, state.w.dtype))
    residual = all_max(jnp.where(live, jnp.abs(state.ratio - mean), 0))
    n_alive = all_sum(alive.astype(dt))
    frac = (all_sum((state.converged & alive).astype(dt))
            / jnp.maximum(n_alive, 1))
    # conservation terms over every row (stranded mass included); the
    # walk's in-flight token carries real mass
    ms = all_sum(state.s)
    mw = all_sum(state.w)
    if hasattr(state, "msg_s"):
        ms = ms + state.msg_s
        mw = mw + state.msg_w
    ms = jnp.sum(ms)  # collapse [d] payload mass to one scalar
    loss = (state.loss if hasattr(state, "loss")
            else jnp.asarray(jnp.nan, dt))
    return jnp.stack([
        residual.astype(dt), frac.astype(dt), ms.astype(dt),
        jnp.asarray(mw, dt), jnp.asarray(loss, dt),
    ])
