from gossipprotocol_tpu.protocols.state import (
    GossipState,
    PushSumState,
    gossip_init,
    pushsum_init,
)
from gossipprotocol_tpu.protocols.gossip import make_gossip_round, gossip_done
from gossipprotocol_tpu.protocols.pushsum import (
    make_pushsum_round,
    pushsum_done,
    mass,
)
from gossipprotocol_tpu.protocols.sampling import make_neighbor_sampler

__all__ = [
    "GossipState",
    "PushSumState",
    "gossip_init",
    "pushsum_init",
    "make_gossip_round",
    "gossip_done",
    "make_pushsum_round",
    "pushsum_done",
    "mass",
    "make_neighbor_sampler",
]
