"""Bulk-synchronous gossip rumor spreading.

Reference semantics (``Actor1``'s ``Process1``/``Process2`` handlers,
``Program.fs:84-98``): an active node repeatedly sends the rumor to one
uniform-random neighbor, skipping receivers the shared dictionary marks
converged (``Program.fs:87-88``); a node converges on hearing the rumor for
the (threshold)-th time. Here one *round* advances every node at once:

  1. every spreading node draws one random neighbor (vectorized),
  2. hits are accumulated by scatter-add (``segment_sum`` — the actor
     version serialized concurrent hits through mailboxes; the scatter-add
     sums them in one XLA op),
  3. hit counts and converged flags update functionally.

Liveness: the reference needs a global keep-alive re-injector actor
(``Actor2``, ``Program.fs:141-163``) because individually-converged
spreaders go silent and can strand a node below threshold. The
bulk-synchronous equivalent is ``keep_alive=True`` (default): nodes that
have heard the rumor keep spreading until *global* convergence — same
intent (keep the rumor alive), no extra entity, no liveness hole.
``keep_alive=False`` reproduces the reference's per-node stop rule
(spreaders go silent at threshold), in which case connected graphs can
stall — bounded by ``max_rounds``.

Divergences from the reference, all documented quirk-vs-capability calls
(SURVEY.md §7 hard part b):
  * converges at the *intended* 10 hits (``README.md:2``), not the
    implemented 11th (``Program.fs:91-92``); ``threshold`` is a knob and
    ``--semantics reference`` sets 11.
  * rounds are synchronous; wall-clock remains the reported metric.
"""

from __future__ import annotations

from functools import partial
import jax
import jax.numpy as jnp

from gossipprotocol_tpu.protocols.sampling import (
    device_topology,
    sample_neighbors,
)
from gossipprotocol_tpu.protocols.state import GossipState
from gossipprotocol_tpu.topology.base import Topology


def gossip_round_core(
    state: GossipState,
    nbrs,  # CSRNeighbors | DenseNeighbors | None (implicit full graph)
    base_key: jax.Array,
    *,
    n: int,
    gids,
    scatter,
    threshold: int = 10,
    keep_alive: bool = True,
    all_alive: bool = False,
) -> GossipState:
    """One synchronous round over the rows in ``gids``.

    The scatter-add is injected so the same body serves both layouts:
    single-chip (``segment_sum`` over [0, n)) and ``shard_map`` (local
    ``segment_sum`` over the padded global length followed by
    ``psum_scatter`` back to local rows). Because per-node draws key on
    global ids, both layouts take bitwise-identical trajectories.

    ``all_alive=True`` (static) compiles out the aliveness masks; legal
    only when no node can ever be dead (see ``pushsum_round_core``).
    """
    key = jax.random.fold_in(base_key, state.round)
    targets, valid = sample_neighbors(nbrs, n, key, gids)

    heard = state.counts >= 1
    spreaders = heard if keep_alive else heard & ~state.converged
    spreaders = spreaders & valid if all_alive else spreaders & valid & state.alive

    hits = scatter(spreaders.astype(state.counts.dtype), targets)
    # the reference's sender-side dict check (Program.fs:87-88) — no hits
    # land on converged or failed receivers. Suppressing on the receiver
    # side is outcome-identical and keeps the rule local to each shard
    # under shard_map (no all-gather of converged flags needed).
    suppressed = state.converged if all_alive else state.converged | ~state.alive
    hits = jnp.where(suppressed, 0, hits)
    counts = state.counts + hits
    converged = state.converged | (counts >= threshold)
    return GossipState(
        counts=counts,
        converged=converged,
        alive=state.alive,
        round=state.round + 1,
    )


@partial(
    jax.jit,
    static_argnames=("n", "threshold", "keep_alive", "all_alive"),
    inline=True,
)
def gossip_round(
    state: GossipState,
    nbrs,  # CSRNeighbors | DenseNeighbors | None (implicit full graph)
    base_key: jax.Array,
    *,
    n: int,
    threshold: int = 10,
    keep_alive: bool = True,
    all_alive: bool = False,
) -> GossipState:
    """Single-chip round. ``nbrs``/``base_key`` are runtime arguments so one
    compiled executable serves every same-shape topology and seed."""
    return gossip_round_core(
        state,
        nbrs,
        base_key,
        n=n,
        gids=None,
        scatter=lambda v, t: jax.ops.segment_sum(v, t, num_segments=n),
        threshold=threshold,
        keep_alive=keep_alive,
        all_alive=all_alive,
    )


def make_gossip_round(
    topo: Topology,
    base_key: jax.Array,
    threshold: int = 10,
    keep_alive: bool = True,
):
    """Closure convenience: bind topology/key, return ``state -> state``."""
    nbrs = device_topology(topo)
    n = topo.num_nodes

    def round_fn(state: GossipState) -> GossipState:
        return gossip_round(
            state, nbrs, base_key, n=n, threshold=threshold, keep_alive=keep_alive
        )

    return round_fn


def gossip_done(state: GossipState) -> jax.Array:
    """Supervisor predicate (reference: ``counter = nodes`` in the scheduler
    actor, ``Program.fs:53``): every healthy node has converged."""
    return jnp.all(state.converged | ~state.alive)
