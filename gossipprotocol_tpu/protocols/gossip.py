"""Bulk-synchronous gossip rumor spreading.

Reference semantics (``Actor1``'s ``Process1``/``Process2`` handlers,
``Program.fs:84-98``): an active node repeatedly sends the rumor to one
uniform-random neighbor, skipping receivers the shared dictionary marks
converged (``Program.fs:87-88``); a node converges on hearing the rumor for
the (threshold)-th time. Here one *round* advances every node at once:

  1. every spreading node draws one random neighbor (vectorized),
  2. hits are accumulated by scatter-add (``segment_sum`` — the actor
     version serialized concurrent hits through mailboxes; the scatter-add
     sums them in one XLA op),
  3. hit counts and converged flags update functionally.

Liveness: the reference needs a global keep-alive re-injector actor
(``Actor2``, ``Program.fs:141-163``) because individually-converged
spreaders go silent and can strand a node below threshold. The
bulk-synchronous equivalent is ``keep_alive=True`` (default): nodes that
have heard the rumor keep spreading until *global* convergence — same
intent (keep the rumor alive), no extra entity, no liveness hole.
``keep_alive=False`` reproduces the reference's per-node stop rule
(spreaders go silent at threshold), in which case connected graphs can
stall — bounded by ``max_rounds``.

Divergences from the reference, all documented quirk-vs-capability calls
(SURVEY.md §7 hard part b):
  * converges at the *intended* 10 hits (``README.md:2``), not the
    implemented 11th (``Program.fs:91-92``); ``threshold`` is a knob and
    ``--semantics reference`` sets 11.
  * rounds are synchronous; wall-clock remains the reported metric.
"""

from __future__ import annotations

from functools import partial
import jax
import jax.numpy as jnp

from gossipprotocol_tpu.protocols.sampling import (
    LOSS_FOLD,
    device_topology,
    drop_mask,
    loss_probability,
    sample_neighbors,
)
from gossipprotocol_tpu.protocols.state import GossipState
from gossipprotocol_tpu.topology.base import Topology


def gossip_round_core(
    state: GossipState,
    nbrs,  # CSRNeighbors | DenseNeighbors | InvertedDense | None (implicit full)
    base_key: jax.Array,
    *,
    n: int,
    gids,
    scatter,
    threshold: int = 10,
    keep_alive: bool = True,
    all_alive: bool = False,
    inverted: bool = False,
    all_sum=jnp.sum,
    loss_windows: tuple = (),
    clock: tuple = (),
) -> GossipState:
    """One synchronous round over the rows in ``gids``.

    The scatter-add is injected so the same body serves both layouts:
    single-chip (``segment_sum`` over [0, n)) and ``shard_map`` (local
    ``segment_sum`` over the padded global length followed by
    ``psum_scatter`` back to local rows). Because per-node draws key on
    global ids, both layouts take bitwise-identical trajectories.

    ``all_alive=True`` (static) compiles out the aliveness masks; legal
    only when no node can ever be dead (see ``pushsum_round_core``).

    ``inverted=True`` (static; requires ``nbrs: InvertedDense``) adds the
    gather-inverted delivery as a second, on-device-selected branch: when
    *every eligible node is spreading* — the ``keep_alive`` steady state
    after the rumor saturates, which dominates runtime at scale — the hit
    histogram is computed receiver-side by :func:`hits_by_inversion`
    (bitwise-equal to the scatter's, measured 3.6x faster at 1M nodes),
    and the sample+scatter branch is skipped entirely. The legality
    condition (``spreaders == valid`` for every row, reduced via
    ``all_sum`` so every shard takes the same branch) is checked each
    round on device, so saturation flips the fast path on mid-chunk and
    a fault-killed node flips it back off automatically.
    """
    key = jax.random.fold_in(base_key, state.round)

    heard = state.counts >= 1
    spreaders = heard if keep_alive else heard & ~state.converged
    if not all_alive:
        spreaders = spreaders & state.alive
    if clock:
        # Poisson activation (async_/clock.py): only rows whose clock
        # ticked spread this round. Config validation pins inverted=False
        # under a poisson clock — the gather inversion assumes every
        # eligible node spreads, which activation breaks every round.
        assert not inverted, "inverted delivery requires the sync clock"
        from gossipprotocol_tpu.async_.clock import activation_mask

        gid_rows_c = (
            gids if gids is not None
            else jnp.arange(state.counts.shape[0], dtype=jnp.int32)
        )
        spreaders = spreaders & activation_mask(key, clock, gid_rows_c)

    if loss_windows:
        # a lost rumor message simply never lands (gossip needs no mass
        # return — the sender's count is untouched by sending)
        p_loss = loss_probability(state.round, loss_windows)
        gid_rows = (
            gids if gids is not None
            else jnp.arange(state.counts.shape[0], dtype=jnp.int32)
        )
        dropped = drop_mask(
            jax.random.fold_in(key, LOSS_FOLD), p_loss, gid_rows
        )
    else:
        dropped = None

    if inverted:
        valid = nbrs.degree > 0
        eligible_spreading = spreaders & valid
        mismatches = all_sum(
            (eligible_spreading != valid).astype(jnp.int32)
        )

        def deliver_inverted():
            return hits_by_inversion(nbrs, key)

        def deliver_scatter():
            targets, valid_s = sample_neighbors(nbrs, n, key, gids)
            send = spreaders & valid_s
            if dropped is not None:
                send = send & ~dropped
            return scatter(send.astype(state.counts.dtype), targets)

        # the inverted gather reproduces the scatter histogram only when
        # every send is delivered; an active loss window breaks that, so
        # the legality check gains a (traced) "no loss right now" term —
        # a pure function of round + static window table, identical on
        # every shard, so all shards still take the same branch
        legal = mismatches == 0
        if loss_windows:
            legal = legal & (p_loss == jnp.float32(0.0))
        hits = jax.lax.cond(legal, deliver_inverted, deliver_scatter)
    else:
        targets, valid = sample_neighbors(nbrs, n, key, gids)
        spreaders = spreaders & valid
        if dropped is not None:
            spreaders = spreaders & ~dropped
        hits = scatter(spreaders.astype(state.counts.dtype), targets)
    # the reference's sender-side dict check (Program.fs:87-88) — no hits
    # land on converged or failed receivers. Suppressing on the receiver
    # side is outcome-identical and keeps the rule local to each shard
    # under shard_map (no all-gather of converged flags needed).
    suppressed = state.converged if all_alive else state.converged | ~state.alive
    hits = jnp.where(suppressed, 0, hits)
    counts = state.counts + hits
    converged = state.converged | (counts >= threshold)
    return GossipState(
        counts=counts,
        converged=converged,
        alive=state.alive,
        round=state.round + 1,
    )


@partial(
    jax.jit,
    static_argnames=(
        "n", "threshold", "keep_alive", "all_alive", "inverted",
        "loss_windows", "clock",
    ),
    inline=True,
)
def gossip_round(
    state: GossipState,
    nbrs,  # CSRNeighbors | DenseNeighbors | InvertedDense | None (implicit full)
    base_key: jax.Array,
    *,
    n: int,
    threshold: int = 10,
    keep_alive: bool = True,
    all_alive: bool = False,
    inverted: bool = False,
    loss_windows: tuple = (),
    clock: tuple = (),
) -> GossipState:
    """Single-chip round. ``nbrs``/``base_key`` are runtime arguments so one
    compiled executable serves every same-shape topology and seed."""
    return gossip_round_core(
        state,
        nbrs,
        base_key,
        n=n,
        gids=None,
        scatter=lambda v, t: jax.ops.segment_sum(v, t, num_segments=n),
        threshold=threshold,
        keep_alive=keep_alive,
        all_alive=all_alive,
        inverted=inverted,
        loss_windows=loss_windows,
        clock=clock,
    )


def make_gossip_round(
    topo: Topology,
    base_key: jax.Array,
    threshold: int = 10,
    keep_alive: bool = True,
):
    """Closure convenience: bind topology/key, return ``state -> state``."""
    nbrs = device_topology(topo)
    n = topo.num_nodes

    def round_fn(state: GossipState) -> GossipState:
        return gossip_round(
            state, nbrs, base_key, n=n, threshold=threshold, keep_alive=keep_alive
        )

    return round_fn


def gossip_message_counts(
    old: GossipState,
    new: GossipState,
    nbrs,
    base_key: jax.Array,
    *,
    n: int,
    gids,
    keep_alive: bool,
    all_alive: bool,
    loss_windows: tuple = (),
    clock: tuple = (),
) -> jax.Array:
    """Telemetry recount of one gossip round: int32 [sent, delivered,
    dropped] over the local rows (obs/counters.py semantics).

    Pure read-only derivation from the (old, new) state pair: ``sent`` is
    the spreader set :func:`gossip_round_core` computed (re-derived from
    ``old`` with the same static flags), ``delivered`` is ΣΔcounts — hits
    actually credited, which is exact in *both* delivery branches (the
    inverted histogram is bitwise-equal to the scatter's), and ``dropped``
    re-draws the same loss mask from the same folded key. Sends suppressed
    by a converged/dead receiver (the reference's dict check) count as
    sent but not delivered — the gap is the protocol's wasted traffic.
    """
    from gossipprotocol_tpu.protocols.sampling import send_valid_mask

    heard = old.counts >= 1
    spreaders = heard if keep_alive else heard & ~old.converged
    if not all_alive:
        spreaders = spreaders & old.alive
    if clock:
        from gossipprotocol_tpu.async_.clock import activation_mask

        key_c = jax.random.fold_in(base_key, old.round)
        gid_rows_c = (
            gids if gids is not None
            else jnp.arange(old.counts.shape[0], dtype=jnp.int32)
        )
        spreaders = spreaders & activation_mask(key_c, clock, gid_rows_c)
    valid = send_valid_mask(nbrs, n, gids)
    sent_mask = spreaders if valid is None else spreaders & valid
    sent = jnp.sum(sent_mask.astype(jnp.int32))
    delivered = (
        jnp.sum(new.counts.astype(jnp.int32))
        - jnp.sum(old.counts.astype(jnp.int32))
    )
    if loss_windows:
        key = jax.random.fold_in(base_key, old.round)
        p_loss = loss_probability(old.round, loss_windows)
        gid_rows = (
            gids if gids is not None
            else jnp.arange(old.counts.shape[0], dtype=jnp.int32)
        )
        drop = drop_mask(jax.random.fold_in(key, LOSS_FOLD), p_loss, gid_rows)
        dropped = jnp.sum((sent_mask & drop).astype(jnp.int32))
    else:
        dropped = jnp.int32(0)
    return jnp.stack([sent, delivered, dropped])


def gossip_trace_row(state, *, all_sum=None, all_max=None) -> jax.Array:
    """Observatory trace row for gossip (column contract in
    :mod:`gossipprotocol_tpu.obs.trace`): the "residual" is the fraction
    of alive nodes the rumor has not reached yet — like push-sum's
    consensus residual it decreases toward 0 on a healthy run, so the
    anomaly stall/divergence rules apply unchanged. Mass and train-loss
    columns are NaN (gossip counts hits; it has no conserved quantity).
    ``all_max`` is accepted for signature parity but unused.
    """
    from gossipprotocol_tpu.protocols.pushsum import sum0

    del all_max
    if all_sum is None:
        all_sum = sum0
    dt = jnp.float32
    alive = state.alive
    n_alive = jnp.maximum(all_sum(alive.astype(dt)), 1)
    heard = all_sum(((state.counts >= 1) & alive).astype(dt))
    frac = all_sum((state.converged & alive).astype(dt)) / n_alive
    nan = jnp.asarray(jnp.nan, dt)
    return jnp.stack([
        (1 - heard / n_alive).astype(dt), frac.astype(dt), nan, nan, nan,
    ])


def gossip_done(state: GossipState) -> jax.Array:
    """Supervisor predicate (reference: ``counter = nodes`` in the scheduler
    actor, ``Program.fs:53``): every healthy node has converged."""
    return jnp.all(state.converged | ~state.alive)


def reverse_slot_table(topo: Topology):
    """Host-side inversion tables for gather-mode hit delivery.

    For every dense-table slot ``(i, k)`` with neighbor ``j = table[i, k]``:

    * ``rev[i, k]`` — the position of ``i`` inside row ``j``'s (sorted)
      neighbor list, i.e. the slot ``j`` must draw for its message to land
      on ``i``;
    * ``deg_nbr[i, k]`` — ``degree[j]``, so ``j``'s slot draw can be
      recomputed elementwise without gathering from the degree vector.

    Built once per topology with one lexsort over the edge list: sorting
    edges by (dst, src) groups each node v's *incoming* edges in exactly
    the order of v's sorted neighbor row, so the rank of an edge within
    its dst block IS the reverse slot. Tables are int8 — the dense path
    is gated at max degree 32, so slots and degrees both fit.
    """
    import numpy as np

    offsets = np.asarray(topo.offsets, dtype=np.int64)
    indices = np.asarray(topo.indices, dtype=np.int64)
    deg = np.asarray(topo.degree, dtype=np.int64)
    n = topo.num_nodes
    maxd = int(deg.max()) if deg.size else 1
    assert maxd < 128, "reverse-slot tables are int8; dense path only"
    row = np.repeat(np.arange(n, dtype=np.int64), np.diff(offsets))
    # the inversion identifies slots by rank within the sorted row, so the
    # CSR must be canonical (csr_from_edges guarantees it; cheap recheck)
    interior = np.ones(len(row), dtype=bool)
    starts = offsets[1:-1]  # first slot of each row (trailing empty rows
    interior[starts[starts < len(row)]] = False  # index past the pool)
    if len(row) > 1:
        assert (np.diff(indices)[interior[1:]] > 0).all(), (
            "reverse_slot_table requires sorted, deduplicated CSR rows"
        )
    order = np.lexsort((row, indices))
    rev_slot = np.empty(len(row), dtype=np.int8)
    rev_slot[order] = (
        np.arange(len(row), dtype=np.int64) - offsets[indices[order]]
    ).astype(np.int8)
    mask = np.arange(max(maxd, 1))[None, :] < deg[:, None]
    rev = np.zeros((n, max(maxd, 1)), dtype=np.int8)
    rev[mask] = rev_slot
    deg_nbr = np.zeros_like(rev)
    deg_nbr[mask] = deg[indices].astype(np.int8)
    return rev, deg_nbr


def inverted_dense(topo: Topology):
    """Device-side :class:`InvertedDense` (dense table + inversion tables)."""
    from gossipprotocol_tpu.protocols.sampling import (
        InvertedDense, dense_table,
    )

    from gossipprotocol_tpu.protocols.sampling import chunked_put

    table, deg = dense_table(topo)
    rev, deg_nbr = reverse_slot_table(topo)
    # chunked: at 100M nodes these tables are multi-GB and a single
    # device_put transaction crashed the remote worker (VERDICT r3 #2)
    return InvertedDense(
        table=chunked_put(table), degree=chunked_put(deg),
        rev=chunked_put(rev), deg_nbr=chunked_put(deg_nbr),
    )


def hits_by_inversion(nbrs, key: jax.Array):
    """Receiver-side hit counting — zero scatters, zero gathers.

    Exact inversion of one round's scatter delivery **when every eligible
    node is spreading** (the ``keep_alive=True`` steady state after the
    rumor saturates): node i's hit count is the number of neighbors whose
    recomputed draw points back at i,

        hits_i = Σ_k [ slot(table[i,k]) == rev[i,k] ],   k < degree[i]

    where ``slot(j)`` reuses the engine's counter-based draw (a pure
    function of the round key and j's global id — the property the
    reference's time-seeded ``System.Random()`` could never offer), so
    the histogram is bitwise-identical to the scatter's. Everything is
    elementwise over the static [rows, max_deg] tables
    (``nbrs: InvertedDense``): under shard_map each device computes its
    own rows' hits with **no collective at all** — draws key on the
    *neighbor* ids already stored in the table, never on who holds them.
    Measured (experiments/gather_invert.py, TPU v5e): 2.39 vs 8.69
    ms/round at 1M imp3D — 3.6x past the "scatter floor".
    """
    from gossipprotocol_tpu.protocols.sampling import recomputed_hits

    return jnp.sum(recomputed_hits(nbrs, key).astype(jnp.int32), axis=1)
