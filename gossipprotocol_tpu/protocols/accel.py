"""Accelerated push-sum averaging: Chebyshev and EPD two-buffer iterations.

Plain diffusion push-sum applies the lazy-random-walk matrix ``W`` once
per round, so the consensus error contracts by the spectral gap — on a
line graph that is O(n²) rounds. Both schemes here are *polynomial
acceleration*: keep the previous iterate and take an affine combination

    x_{t+1} = a_t · W x_t + (1 − a_t) · x_{t−1}

whose coefficients sum to 1, so Σx is conserved exactly whenever ``W``
conserves it (the property tests pin this). Applied identically to the
``s`` payload and the ``w`` weight stream, the de-biased ratio ``s/w``
converges at the accelerated O(1/√gap) rate — the push-sum form of the
schemes, as in the Euler-Poisson-Darboux gossip paper (arXiv:2202.10742)
and Chebyshev-accelerated gossip (arXiv:2011.02379).

* ``chebyshev`` — the classical semi-iterative weights (Golub–Varga):
  ω₁ = 1, ω₂ = 1/(1 − γ²/2), ω_{t+1} = 1/(1 − (γ²/4)·ω_t), where γ is
  (an upper bound on) the second-largest eigenvalue magnitude of ``W``.
  Optimal among polynomial schemes when γ is tight; supplied via
  ``--accel-lambda`` or estimated host-side by :func:`estimate_gamma`.
* ``epd`` — parameter-free: a_t = (2t + δ)/(t + δ) with δ = 1. No
  spectral knowledge needed; asymptotically the wave-equation
  discretization x_{t+1} ≈ 2·W x_t − x_{t−1}.

Both run the same delivery (fanout-all scatter diffusion), the same
predicate tail, and the same telemetry as plain push-sum. They assume a
*fixed* mixing matrix: RunConfig rejects ``--accel`` combined with fault
schedules, loss windows, or repair.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from gossipprotocol_tpu.protocols.diffusion import diffusion_mix
from gossipprotocol_tpu.protocols.pushsum import finish_pushsum_round, sum0
from gossipprotocol_tpu.protocols.state import AccelState, pushsum_init
from gossipprotocol_tpu.topology.base import Topology

EPD_DELTA = 1.0


def accel_init(
    num_nodes: int,
    value_mode: str = "scaled",
    dtype=jnp.float32,
    real_nodes: int | None = None,
    payload_dim: int = 1,
) -> AccelState:
    """Push-sum init plus the second buffer. ``s_prev = s₀`` is arbitrary:
    both schemes put weight 0 on it at t = 0."""
    ps = pushsum_init(
        num_nodes, value_mode=value_mode, dtype=dtype,
        real_nodes=real_nodes, payload_dim=payload_dim,
    )
    return AccelState(
        # jnp.copy: distinct buffers — the chunk runner donates the state,
        # and XLA rejects the same buffer donated twice
        *ps, s_prev=jnp.copy(ps.s), w_prev=jnp.copy(ps.w),
        omega=jnp.asarray(0, dtype),
    )


def accel_coefficient(round_idx: jax.Array, omega, *, variant: str,
                      gamma: float, dtype):
    """(a_t, ω_{t+1}) for the affine combination at round ``round_idx``."""
    one = jnp.asarray(1, dtype)
    if variant == "epd":
        t = round_idx.astype(dtype)
        a = (2 * t + EPD_DELTA) / (t + EPD_DELTA)
        return a, omega
    g2 = jnp.asarray(gamma * gamma, dtype)
    om_next = jnp.where(
        round_idx == 0,
        one,
        jnp.where(
            round_idx == 1,
            1 / (1 - g2 * 0.5),
            1 / (1 - g2 * 0.25 * omega),
        ),
    )
    return om_next, om_next


def accel_round_core(
    state: AccelState,
    nbrs,
    base_key: jax.Array,
    *,
    n: int,
    scatter,
    alive_global,
    variant: str,
    gamma: float = 0.0,
    eps: float = 1e-10,
    streak_target: int = 3,
    predicate: str = "delta",
    tol: float = 1e-4,
    all_sum=sum0,
    all_alive: bool = False,
    targets_alive: bool = False,
    edge_chunks: int = 1,
    row_offset=0,
) -> AccelState:
    """One accelerated round: W-apply via the shared diffusion mix, then
    the two-buffer affine combination, then the shared predicate tail."""
    dt = state.w.dtype
    mix_s, mix_w, in_w = diffusion_mix(
        state, nbrs, base_key,
        n=n, scatter=scatter, alive_global=alive_global, all_sum=all_sum,
        all_alive=all_alive, targets_alive=targets_alive,
        edge_chunks=edge_chunks, loss_windows=(), row_offset=row_offset,
    )
    a, om_next = accel_coefficient(
        state.round, state.omega, variant=variant, gamma=gamma, dtype=dt)
    b = 1 - a
    s_next = a * mix_s + b * state.s_prev
    w_next = a * mix_w + b * state.w_prev
    st = finish_pushsum_round(
        state, s_next, w_next,
        received=in_w > 0, eps=eps, streak_target=streak_target,
        reference_semantics=False, predicate=predicate, tol=tol,
        all_sum=all_sum, all_alive=all_alive,
    )
    return st._replace(s_prev=state.s, w_prev=state.w, omega=om_next)


@partial(
    jax.jit,
    static_argnames=(
        "n", "variant", "gamma", "eps", "streak_target", "predicate",
        "tol", "all_alive", "targets_alive", "edge_chunks",
    ),
    inline=True,
)
def accel_round(
    state: AccelState,
    nbrs,
    base_key: jax.Array,
    *,
    n: int,
    variant: str,
    gamma: float = 0.0,
    eps: float = 1e-10,
    streak_target: int = 3,
    predicate: str = "delta",
    tol: float = 1e-4,
    all_alive: bool = False,
    targets_alive: bool = False,
    edge_chunks: int = 1,
) -> AccelState:
    """Single-chip accelerated round (same call shape as
    ``pushsum_diffusion_round``)."""

    def scatter(a, b, dst):
        return (
            jax.ops.segment_sum(a, dst, num_segments=n),
            jax.ops.segment_sum(b, dst, num_segments=n),
        )

    return accel_round_core(
        state, nbrs, base_key,
        n=n, scatter=scatter, alive_global=state.alive,
        variant=variant, gamma=gamma, eps=eps,
        streak_target=streak_target, predicate=predicate, tol=tol,
        all_alive=all_alive, targets_alive=targets_alive,
        edge_chunks=edge_chunks,
    )


def estimate_gamma(topo: Topology, iters: int = 200, seed: int = 0) -> float:
    """Host-side power-iteration estimate of γ = |λ₂(W)| for the lazy
    random walk ``W = (I + A) D̂⁻¹`` (D̂ = deg + 1), i.e. exactly the
    mixing matrix diffusion applies.

    ``W`` is column-stochastic (mass-conserving), so its left principal
    eigenvector is 𝟙 with eigenvalue 1; the right principal eigenvector π
    comes from a first power iteration, then the deflated operator
    ``W' = W − π𝟙ᵀ/(𝟙ᵀπ)`` is power-iterated for |λ₂|. O(iters · E) on
    host numpy — fine up to a few million edges; ``--accel-lambda``
    overrides for bigger graphs or known spectra.
    """
    if topo.implicit_full:
        # K_n diffusion mixes in one round; Chebyshev degenerates to plain
        return 0.0
    if hasattr(topo, "csr_slice"):
        raise ValueError(
            "γ estimation power-iterates the global CSR on the host, "
            "which a streamed topology build never materializes — pass "
            "--accel-lambda or use --build materialized")
    n = topo.num_nodes
    offsets = np.asarray(topo.offsets, dtype=np.int64)
    src = np.repeat(np.arange(n, dtype=np.int64), np.diff(offsets))
    dst = np.asarray(topo.indices, dtype=np.int64)
    inv = 1.0 / (np.asarray(topo.degree, dtype=np.float64) + 1.0)

    def apply_w(x):
        xh = x * inv
        return xh + np.bincount(src, weights=xh[dst], minlength=n)

    rng = np.random.default_rng(seed)
    pi = np.abs(rng.standard_normal(n)) + 1e-3
    for _ in range(iters):
        pi = apply_w(pi)
        pi /= np.linalg.norm(pi)
    pi_sum = float(pi.sum())

    z = rng.standard_normal(n)
    lam = 0.0
    for _ in range(iters):
        z = apply_w(z) - pi * (z.sum() / pi_sum)
        norm = np.linalg.norm(z)
        if norm < 1e-300:
            return 0.0
        lam = norm
        z /= norm
    return float(min(lam, 1.0 - 1e-9))
