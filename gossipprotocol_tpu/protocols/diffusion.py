"""Fanout-all diffusion push-sum (``--fanout all``).

The reference's sender emits exactly **one** message per handler
invocation (``Program.fs:128``) — a quirk of its actor loop, not the
claimed capability (distributed averaging). Single-target push-sum needs
O(max_degree) rounds to drain a hub on power-law graphs (each incoming
edge delivers with probability 1/deg per round), which makes the 10M-node
power-law north-star config unreachable under any round budget. The
diffusion variant implemented here is the standard fix: every round, every
node keeps ``1/(deg+1)`` of its ``(s, w)`` and ships one ``1/(deg+1)``
share to *each* neighbor. That is exactly the lazy random-walk transition
matrix ``P = (I + A·D⁻¹)/…`` applied to the mass vectors, so estimates
converge at the graph's mixing time — O(log n / spectral gap), ~tens of
rounds on Barabási–Albert graphs — while conserving Σs, Σw exactly like
the single-target variant.

TPU shape: no random draws at all. Delivery is one ``segment_sum`` over
the symmetric CSR edge list (src sorted — XLA turns the per-edge share
gather into near-sequential reads; the dst scatter is the same
random-scatter kernel the single-target round pays, scaled E/N). Under
``shard_map`` the edge list itself is sharded by source block (each
device owns exactly the out-edges of its row block, host-localized
indices, padded to equal length), partial sums land in a full-length
vector, and one ``psum_scatter`` delivers each device its own rows — the
identical collective pattern as the single-target round.

The complete graph needs no edges at all: every share goes everywhere, so
``in_i = Σ_j share_j − share_i`` is two reductions (a ``psum`` under
shard_map) — and K_n diffusion provably mixes in **one** round
(``s_new_i = Σ_j s_j / n`` for every i).

``semantics="reference"`` is rejected for this variant (`RunConfig`):
the single-target send *is* the reference's accidental behavior that
fanout-all replaces.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gossipprotocol_tpu.protocols.pushsum import (
    finish_pushsum_round,
    rowmask,
    sum0,
)
from gossipprotocol_tpu.protocols.state import PushSumState
from gossipprotocol_tpu.topology.base import Topology


class DiffusionEdges(NamedTuple):
    """Device-side edge list for fanout-all delivery (a pytree).

    Single-chip: ``src``/``dst`` are the CSR (row, col) pairs, sorted by
    src, all valid. Under ``shard_map`` the arrays are the concatenation
    of per-device blocks (equal length, zero-padded): ``src`` is
    **device-local** row indices, ``dst`` stays global (it feeds the
    full-length scatter that ``psum_scatter`` then distributes).
    ``degree`` is row-aligned with the state (shards with it).
    """

    src: jax.Array     # int32[E']  edge source, local row index
    dst: jax.Array     # int32[E']  edge target, global node id
    valid: Optional[jax.Array]  # bool[E'] False on padding edges; None =
                       # all valid (single-chip: 0.76 GB saved at 100M)
    degree: jax.Array  # int32[rows]


def diffusion_edges(topo: Topology) -> Optional[DiffusionEdges]:
    """Single-chip device arrays; None for the implicit complete graph."""
    if topo.implicit_full:
        return None
    n = topo.num_nodes
    src = np.repeat(np.arange(n, dtype=np.int32), np.diff(topo.offsets))
    return DiffusionEdges(
        src=jnp.asarray(src),
        dst=jnp.asarray(topo.indices, dtype=jnp.int32),
        valid=None,  # single-chip CSR: every edge is real
        degree=jnp.asarray(topo.degree, dtype=jnp.int32),
    )


def sharded_diffusion_edges(
    topo: Topology, n_padded: int, num_shards: int
) -> Optional[DiffusionEdges]:
    """Host-side split of the edge list by source row block.

    Device ``d`` owns the out-edges of rows ``[d·local_n, (d+1)·local_n)``
    — CSR order means that is one contiguous slice per device. Each block
    is padded to the longest block's length so the leading axis splits
    evenly over the mesh; ``src`` is localized (block offset subtracted)
    because each device gathers shares from its *local* state rows.
    """
    if topo.implicit_full:
        return None
    n = topo.num_nodes
    local_n = n_padded // num_shards
    offsets = np.asarray(topo.offsets, dtype=np.int64)
    src = np.repeat(np.arange(n, dtype=np.int64), np.diff(offsets))
    dst = np.asarray(topo.indices, dtype=np.int32)
    # edge index boundaries of each device's row block (rows >= n have no
    # edges, so clipping the row range into [0, n] is exact)
    bounds = offsets[np.clip(np.arange(num_shards + 1) * local_n, 0, n)]
    counts = np.diff(bounds)
    max_e = max(int(counts.max()), 1)
    src_l = np.zeros((num_shards, max_e), dtype=np.int32)
    dst_l = np.zeros((num_shards, max_e), dtype=np.int32)
    valid = np.zeros((num_shards, max_e), dtype=bool)
    for d in range(num_shards):
        lo, hi = int(bounds[d]), int(bounds[d + 1])
        c = hi - lo
        src_l[d, :c] = src[lo:hi] - d * local_n
        dst_l[d, :c] = dst[lo:hi]
        valid[d, :c] = True
    degree = np.zeros(n_padded, dtype=np.int32)
    degree[:n] = topo.degree
    return DiffusionEdges(
        src=jnp.asarray(src_l.reshape(-1)),
        dst=jnp.asarray(dst_l.reshape(-1)),
        valid=jnp.asarray(valid.reshape(-1)),
        degree=jnp.asarray(degree),
    )


def diffusion_mix(
    state,
    nbrs: Optional[DiffusionEdges],
    base_key: jax.Array,
    *,
    n: int,
    scatter,
    alive_global,
    all_sum=sum0,
    all_alive: bool = False,
    targets_alive: bool = False,
    edge_chunks: int = 1,
    loss_windows: tuple = (),
    row_offset=0,
    clock: tuple = (),
):
    """The lazy-random-walk mixing step alone: returns
    ``(s_new, w_new, in_w)`` with no predicate applied.

    Extracted from the full round so the accelerated variants
    (:mod:`protocols.accel`) can apply ``W x_t`` and then affine-combine
    with the previous iterate before running the shared predicate tail.
    Payload-polymorphic: ``state.s`` may be ``[rows]`` or ``[rows, d]``
    (``w`` always per-node); the d=1 trace is the pre-vector program.

    ``clock`` (static; :mod:`gossipprotocol_tpu.async_`) zeroes the
    outgoing shares of rows whose Poisson clock did not tick — delivery
    is linear in the shares, so every downstream accounting term
    (``sent = share·deg``, the delivered-count path, the implicit-full
    reductions) is automatically exact and mass stays conserved. Unlike
    per-edge loss, activation is per-*node*, so the implicit complete
    graph is legal under a poisson clock.
    """
    dt = state.w.dtype
    if loss_windows:
        from gossipprotocol_tpu.protocols.sampling import (
            LOSS_FOLD, drop_mask, loss_probability,
        )
        assert nbrs is not None, (
            "per-edge loss needs an explicit edge list; the implicit "
            "complete graph has none (RunConfig validation rejects this)"
        )
        key_loss = jax.random.fold_in(
            jax.random.fold_in(base_key, state.round), LOSS_FOLD
        )
        p_loss = loss_probability(state.round, loss_windows)
    elif not clock:
        del base_key  # deterministic: fanout-all draws nothing

    if clock:
        from gossipprotocol_tpu.async_.clock import activation_mask

        gid_rows = row_offset + jnp.arange(
            state.w.shape[0], dtype=jnp.int32
        )
        active = activation_mask(
            jax.random.fold_in(base_key, state.round), clock, gid_rows
        )
    else:
        active = None

    if nbrs is None:
        # Implicit complete graph: in_i = Σ share − share_i. Mixes in one
        # round (s_new_i = Σ s_j / A for every i).
        if all_alive:
            a_count = jnp.asarray(n, dt)
            s_m, w_m = state.s, state.w
        else:
            a_count = jnp.maximum(
                all_sum(state.alive.astype(dt)), jnp.asarray(1, dt)
            )
            s_m = jnp.where(rowmask(state.alive, state.s), state.s, 0)
            w_m = jnp.where(state.alive, state.w, 0)
        share_s = s_m / a_count
        share_w = w_m / a_count
        if active is not None:
            # an idle node ships nothing; in_i = Σ share − share_i still
            # holds because its own (zero) share subtracts out
            share_s = jnp.where(rowmask(active, share_s), share_s, 0)
            share_w = jnp.where(active, share_w, 0)
        in_s = all_sum(share_s) - share_s
        in_w = all_sum(share_w) - share_w
        sent_s = share_s * (a_count - 1)
        sent_w = share_w * (a_count - 1)
        if not all_alive:
            in_s = jnp.where(rowmask(state.alive, in_s), in_s, 0)
            in_w = jnp.where(state.alive, in_w, 0)
        return state.s - sent_s + in_s, state.w - sent_w + in_w, in_w

    rows = state.w.shape[0]
    deg = nbrs.degree.astype(dt)
    inv = 1 / (deg + 1)
    share_s = state.s * rowmask(inv, state.s)
    share_w = state.w * inv
    if not all_alive:
        share_s = jnp.where(rowmask(state.alive, share_s), share_s, 0)
        share_w = jnp.where(state.alive, share_w, 0)
    if active is not None:
        share_s = jnp.where(rowmask(active, share_s), share_s, 0)
        share_w = jnp.where(active, share_w, 0)

    # Delivery, optionally in ``edge_chunks`` sequential slices: the
    # per-edge intermediates (gathered shares, deliver masks) are the
    # memory peak of a diffusion round — 18.07 GB vs 15.75 GB HBM at
    # 100M nodes (VERDICT r3 weak #3). K slices shrink them K-fold and
    # trade nothing but kernel-launch count; trajectories match the
    # unchunked round to float accumulation order (partial in-vectors
    # add per slice).
    zero = jnp.asarray(0, dt)
    e_total = nbrs.src.shape[0]
    bounds = [e_total * k // edge_chunks for k in range(edge_chunks + 1)]
    in_s = jnp.zeros(share_s.shape, dt)
    in_w = jnp.zeros(rows, dt)
    fast_alive = all_alive or targets_alive
    # the delivered-count makes ``sent = share · cnt`` exact whenever any
    # edge can fail to deliver — dead targets or dropped messages alike
    needs_cnt = bool(loss_windows) or not fast_alive
    cnt = jnp.zeros(rows, dt) if needs_cnt else None
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        if hi == lo:
            continue
        src_k = jax.lax.slice_in_dim(nbrs.src, lo, hi)
        dst_k = jax.lax.slice_in_dim(nbrs.dst, lo, hi)
        val_k = (None if nbrs.valid is None
                 else jax.lax.slice_in_dim(nbrs.valid, lo, hi))
        # src is sorted (CSR order), so this gather streams
        es = share_s[src_k]
        ew = share_w[src_k]
        if fast_alive:
            deliver = val_k            # None = every edge delivers
        else:
            # arbitrary dead sets (mid-run faults): an edge delivers
            # only if its target is alive; the sender keeps undelivered
            # shares so mass stays conserved among all rows
            alive_k = alive_global[dst_k]
            deliver = alive_k if val_k is None else (val_k & alive_k)
        if loss_windows:
            keep = ~drop_mask(
                key_loss, p_loss, src_k + row_offset, dst_k
            )
            deliver = keep if deliver is None else (deliver & keep)
        if needs_cnt:
            cnt = cnt + jax.ops.segment_sum(
                (jnp.ones(src_k.shape, dt) if deliver is None
                 else deliver.astype(dt)),
                src_k, num_segments=rows,
            )
        if deliver is None:
            d_s, d_w = scatter(es, ew, dst_k)
        else:
            d_s, d_w = scatter(
                jnp.where(rowmask(deliver, es), es, zero),
                jnp.where(deliver, ew, zero),
                dst_k,
            )
        in_s = in_s + d_s
        in_w = in_w + d_w
    if needs_cnt:
        sent_s = share_s * rowmask(cnt, share_s)
        sent_w = share_w * cnt
    else:
        sent_s = share_s * rowmask(deg, share_s)
        sent_w = share_w * deg
    return state.s - sent_s + in_s, state.w - sent_w + in_w, in_w


def pushsum_diffusion_round_core(
    state: PushSumState,
    nbrs: Optional[DiffusionEdges],
    base_key: jax.Array,
    *,
    n: int,
    scatter,
    alive_global,
    eps: float = 1e-10,
    streak_target: int = 3,
    predicate: str = "delta",
    tol: float = 1e-4,
    all_sum=sum0,
    all_alive: bool = False,
    targets_alive: bool = False,
    edge_chunks: int = 1,
    loss_windows: tuple = (),
    row_offset=0,
    clock: tuple = (),
) -> PushSumState:
    """One synchronous fanout-all round.

    ``scatter(a_e, b_e, dst_e) -> (in_a, in_b)`` is injected like the
    single-target round's: a plain ``segment_sum`` single-chip, partial
    ``segment_sum`` + ``psum_scatter`` under ``shard_map``. The liveness
    fast-path flags carry the exact same legality contract as
    :func:`pushsum_round_core` (``all_alive``: nobody can die;
    ``targets_alive``: the dead set is component-closed, so an alive
    node's neighbors are alive and no per-edge target-liveness gather is
    needed — dead→dead edges ship a zero share and deliver nothing).

    ``loss_windows`` adds a per-directed-edge Bernoulli drop mask keyed on
    the **global** (src, dst) pair — ``row_offset`` globalizes the local
    ``src`` indices under ``shard_map`` — so the mask is sharding-
    invariant. A dropped edge's share stays with the sender via the same
    delivered-count accounting the dead-target path uses.
    """
    s_new, w_new, in_w = diffusion_mix(
        state,
        nbrs,
        base_key,
        n=n,
        scatter=scatter,
        alive_global=alive_global,
        all_sum=all_sum,
        all_alive=all_alive,
        targets_alive=targets_alive,
        edge_chunks=edge_chunks,
        loss_windows=loss_windows,
        row_offset=row_offset,
        clock=clock,
    )
    return finish_pushsum_round(
        state, s_new, w_new,
        received=in_w > 0, eps=eps, streak_target=streak_target,
        reference_semantics=False, predicate=predicate, tol=tol,
        all_sum=all_sum, all_alive=all_alive,
    )


_INT32_MAX_F = float(np.iinfo(np.int32).max)


def _clip_count(x) -> jax.Array:
    """f32 message count -> int32, saturating (implicit-full rounds can
    exceed INT32_MAX messages above ~46k alive nodes)."""
    return jnp.clip(
        x.astype(jnp.float32), 0.0, _INT32_MAX_F
    ).astype(jnp.int32)


def diffusion_message_counts(
    old: PushSumState,
    nbrs: Optional[DiffusionEdges],
    base_key: jax.Array,
    *,
    n: int,
    gids,
    all_alive: bool,
    targets_alive: bool,
    loss_windows: tuple,
    alive_global,
    all_sum=jnp.sum,
    clock: tuple = (),
) -> jax.Array:
    """Telemetry recount of one fanout-all scatter round: int32 [sent,
    delivered, dropped] over the local rows (obs/counters.py semantics).

    Walks the same edge list with the same per-edge masks (validity,
    target liveness, the (global src, global dst)-keyed drop mask) the
    round applied — read-only, one extra pass over E per round while
    telemetry is on. The implicit complete graph has no edges: every
    alive node attempts ``a − 1`` sends and all land (loss is rejected
    there by config), counted via ``all_sum`` and saturated to int32.
    ``gids`` globalizes the local ``src`` ids under shard_map
    (``row_offset = gids[0]``); None single-chip.
    """
    if clock:
        from gossipprotocol_tpu.async_.clock import activation_mask

        gid_rows_c = (
            gids if gids is not None
            else jnp.arange(old.w.shape[0], dtype=jnp.int32)
        )
        active = activation_mask(
            jax.random.fold_in(base_key, old.round), clock, gid_rows_c
        )
    else:
        active = None

    if nbrs is None:
        dt = old.s.dtype
        send_rows = old.alive if not all_alive else None
        if active is not None:
            send_rows = (active if send_rows is None
                         else (send_rows & active))
        if all_alive:
            a = jnp.asarray(n, jnp.float32)
        else:
            a = all_sum(old.alive.astype(jnp.float32))
        local = (
            jnp.asarray(old.s.shape[0], jnp.float32) if send_rows is None
            else jnp.sum(send_rows.astype(jnp.float32))
        )
        del dt
        cnt = _clip_count(local * jnp.maximum(a - 1.0, 0.0))
        return jnp.stack([cnt, cnt, jnp.int32(0)])

    src_alive = None if all_alive else old.alive[nbrs.src]
    mask = nbrs.valid
    if src_alive is not None:
        mask = src_alive if mask is None else (mask & src_alive)
    if active is not None:
        src_active = active[nbrs.src]
        mask = src_active if mask is None else (mask & src_active)
    sent = (
        jnp.asarray(nbrs.src.shape[0], jnp.int32) if mask is None
        else jnp.sum(mask.astype(jnp.int32))
    )
    deliver = mask
    if not (all_alive or targets_alive):
        tgt_alive = alive_global[nbrs.dst]
        deliver = tgt_alive if deliver is None else (deliver & tgt_alive)
    if loss_windows:
        from gossipprotocol_tpu.protocols.sampling import (
            LOSS_FOLD, drop_mask, loss_probability,
        )

        key_loss = jax.random.fold_in(
            jax.random.fold_in(base_key, old.round), LOSS_FOLD
        )
        p_loss = loss_probability(old.round, loss_windows)
        row_offset = jnp.int32(0) if gids is None else gids[0]
        keep = ~drop_mask(key_loss, p_loss, nbrs.src + row_offset, nbrs.dst)
        if deliver is None:
            dropped = jnp.sum((~keep).astype(jnp.int32))
            deliver = keep
        else:
            dropped = jnp.sum((deliver & ~keep).astype(jnp.int32))
            deliver = deliver & keep
    else:
        dropped = jnp.int32(0)
    delivered = (
        sent if deliver is None else jnp.sum(deliver.astype(jnp.int32))
    )
    return jnp.stack([sent, delivered, dropped])


def routed_message_counts(
    old: PushSumState,
    routed,  # ops.delivery.RoutedDelivery
    *,
    n: int,
    all_alive: bool,
    targets_alive: bool,
    interpret: bool = False,
    base_key=None,
    clock: tuple = (),
) -> jax.Array:
    """Telemetry recount of one single-chip routed round (obs/counters.py).

    Routed delivery ships one share per directed edge of a live sender
    and rejects loss windows by config, so ``dropped`` is always 0 and
    ``sent`` is Σ degree over live rows. ``delivered`` equals ``sent``
    on the fast paths; under an arbitrary dead set the round already
    recovers per-node live-neighbor counts algebraically with one extra
    ``matvec(alive, alive)`` — the recount repeats it (doubling to two
    extra matvecs per round while faults are in force and telemetry on).
    """
    dt = old.s.dtype
    rows = old.s.shape[0]
    deg = routed.degree.astype(dt)
    if rows > n:
        deg = jnp.pad(deg, (0, rows - n))
    if clock:
        # only rows whose clock ticked shipped their shares this round
        from gossipprotocol_tpu.async_.clock import activation_mask

        active = activation_mask(
            jax.random.fold_in(base_key, old.round), clock,
            jnp.arange(rows, dtype=jnp.int32),
        )
        deg = jnp.where(active, deg, 0)
    if all_alive:
        sent = _clip_count(jnp.sum(deg))
        return jnp.stack([sent, sent, jnp.int32(0)])
    live_rows = jnp.where(old.alive, deg, 0)
    sent = _clip_count(jnp.sum(live_rows))
    if targets_alive:
        return jnp.stack([sent, sent, jnp.int32(0)])
    alive_f = old.alive.astype(dt)
    live_deg, _ = routed.matvec(alive_f, alive_f, interpret=interpret)
    if clock:
        live_deg = jnp.where(active, live_deg, 0)
    delivered = _clip_count(
        jnp.sum(jnp.where(old.alive, live_deg, 0))
    )
    return jnp.stack([sent, delivered, jnp.int32(0)])


@partial(
    jax.jit,
    static_argnames=(
        "n", "eps", "streak_target", "predicate", "tol", "all_alive",
        "targets_alive", "interpret", "clock",
    ),
    inline=True,
)
def pushsum_diffusion_round_routed(
    state: PushSumState,
    routed,  # ops.delivery.RoutedDelivery (registered pytree)
    base_key: jax.Array,
    *,
    n: int,
    eps: float = 1e-10,
    streak_target: int = 3,
    predicate: str = "delta",
    tol: float = 1e-4,
    all_alive: bool = False,
    targets_alive: bool = False,
    interpret: bool = False,
    clock: tuple = (),
) -> PushSumState:
    """Fanout-all round with the routed (scatter-free) delivery.

    Same mathematics as :func:`pushsum_diffusion_round` — every node
    keeps ``1/(deg+1)`` of ``(s, w)`` and ships one share per edge — but
    delivery runs through the static routing plans of
    :mod:`gossipprotocol_tpu.ops.delivery` instead of two random-index
    ``segment_sum`` scatters. Trajectories equal the scatter path to
    float accumulation order.

    Fast paths (``all_alive`` / ``targets_alive``) ship every share and
    keep ``sent = share · deg``. Under an **arbitrary** dead set
    (mid-run fault strikes) the static plan can't mask per-edge targets,
    so the general path recovers exactness algebraically: one extra
    ``matvec(alive, alive)`` yields each node's count of *alive*
    neighbors (``live_deg``, exact small-integer floats), the received
    sums are masked to alive rows, and ``sent = share · live_deg`` — the
    same values the scatter path's delivered-count accounting produces,
    at ~1.5× the per-round cost while a fault plan is in force.
    """
    from gossipprotocol_tpu.ops.delivery import (
        mask_sender_rows, matvec_payload,
    )

    if not clock:
        del base_key  # deterministic: fanout-all draws nothing
    dt = state.w.dtype
    rows = state.w.shape[0]
    deg = routed.degree.astype(dt)
    if rows > n:
        deg = jnp.pad(deg, (0, rows - n))
    inv = 1 / (deg + 1)
    share_s = state.s * rowmask(inv, state.s)
    share_w = state.w * inv
    if not all_alive:
        share_s = jnp.where(rowmask(state.alive, share_s), share_s, 0)
        share_w = jnp.where(state.alive, share_w, 0)
    if clock:
        # routed plans are static linear operators: idle senders are
        # expressed purely by zeroing their input rows, the plan itself
        # never changes (ops/delivery.py mask_sender_rows)
        share_s, share_w = mask_sender_rows(
            share_s, share_w,
            jax.random.fold_in(base_key, state.round), clock,
            jnp.arange(rows, dtype=jnp.int32),
        )
    in_s, in_w = matvec_payload(
        lambda a, b: routed.matvec(a, b, interpret=interpret),
        share_s, share_w,
    )
    if all_alive or targets_alive:
        sent_s = share_s * rowmask(deg, share_s)
        sent_w = share_w * deg
    else:
        alive_f = state.alive.astype(dt)
        live_deg, _ = routed.matvec(alive_f, alive_f, interpret=interpret)
        # a dead receiver's in-sum is garbage only to itself: discard it
        # (the sender already kept that share via live_deg below)
        in_s = jnp.where(rowmask(state.alive, in_s), in_s, 0)
        in_w = jnp.where(state.alive, in_w, 0)
        sent_s = share_s * rowmask(live_deg, share_s)
        sent_w = share_w * live_deg
    return finish_pushsum_round(
        state, state.s - sent_s + in_s, state.w - sent_w + in_w,
        received=in_w > 0, eps=eps, streak_target=streak_target,
        reference_semantics=False, predicate=predicate, tol=tol,
        all_sum=sum0, all_alive=all_alive,
    )


@partial(
    jax.jit,
    static_argnames=(
        "n", "eps", "streak_target", "predicate", "tol", "all_alive",
        "targets_alive", "edge_chunks", "loss_windows", "clock",
    ),
    inline=True,
)
def pushsum_diffusion_round(
    state: PushSumState,
    nbrs: Optional[DiffusionEdges],
    base_key: jax.Array,
    *,
    n: int,
    eps: float = 1e-10,
    streak_target: int = 3,
    predicate: str = "delta",
    tol: float = 1e-4,
    all_alive: bool = False,
    targets_alive: bool = False,
    edge_chunks: int = 1,
    loss_windows: tuple = (),
    clock: tuple = (),
) -> PushSumState:
    """Single-chip fanout-all round (same call shape as ``pushsum_round``)."""

    def scatter(a, b, dst):
        return (
            jax.ops.segment_sum(a, dst, num_segments=n),
            jax.ops.segment_sum(b, dst, num_segments=n),
        )

    return pushsum_diffusion_round_core(
        state,
        nbrs,
        base_key,
        n=n,
        scatter=scatter,
        alive_global=state.alive,
        eps=eps,
        streak_target=streak_target,
        predicate=predicate,
        tol=tol,
        all_alive=all_alive,
        targets_alive=targets_alive,
        edge_chunks=edge_chunks,
        loss_windows=loss_windows,
        clock=clock,
    )


def diffusion_trace_row(state, *, all_sum=sum0, all_max=jnp.max):
    """Observatory trace row for fanout-all diffusion (and the accelerated
    two-buffer variants): diffusion shares ``PushSumState``'s (s, w, ratio)
    fields, so the row IS push-sum's — one definition, re-exported here so
    the obs dispatch mirrors build_protocol branch-for-branch."""
    from gossipprotocol_tpu.protocols.pushsum import pushsum_trace_row

    return pushsum_trace_row(state, all_sum=all_sum, all_max=all_max)
