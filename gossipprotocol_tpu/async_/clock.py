"""Per-node activation clocks, counter-based like every other draw.

The continuous-time gossip model (arXiv:2011.02379) puts an independent
rate-``r`` Poisson clock on every node; a node pushes when its clock
ticks. Discretizing to unit-length rounds thins the process: the number
of rounds in which node ``i`` is active is Binomial(R, p) with
``p = 1 - exp(-r)`` — the probability the node's clock ticked at least
once inside the round. Receivers stay passive (receipt needs no clock),
which is exactly the paper's single-activation push model.

The activation mask is drawn by the same threefry-on-global-ids pattern
as the fault engine's loss windows (:func:`protocols.sampling.drop_mask`),
so the trajectory is a pure function of (seed, round, gid): identical
under any sharding, reproducible for a fixed seed, and free — the mask
is a trace-time branch, absent from the compiled program when the clock
is synchronous.

A clock spec is a static hashable tuple so it can ride jit
``static_argnames`` next to ``loss_windows``:

* ``()``            — synchronous clock; every node acts every round.
* ``(rate, id_div)`` — Poisson clock with activation rate ``rate``;
  activation coins are keyed on ``gid // id_div``. ``id_div = 1`` gives
  independent per-node clocks; the GALA workload passes the learner
  group size so a whole group shares one clock and gossips as a unit.
* ``("prob", p, id_div)`` — Poisson clock with the per-round activation
  probability supplied directly as ``p``, which may be a *traced* f32
  scalar. The sweep engine uses this to thread per-lane activation
  rates through one vmapped program; ``p`` must be the host-rounded
  ``float32(1 - exp(-rate))`` so lanes stay bitwise equal to the
  static-rate program.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

from gossipprotocol_tpu.protocols.sampling import drop_mask

# Domain-separation constant folded into the round key before activation
# draws. Distinct from sampling.LOSS_FOLD (0x10553) so a run with both
# packet loss and a Poisson clock draws two independent coin streams —
# sharing the fold would correlate "my message was dropped" with "my
# clock ticked" perfectly.
CLOCK_FOLD = 0xA51C


def clock_spec(clock: str, activation_rate: float, id_div: int = 1) -> Tuple:
    """Build the static clock-spec tuple from config values.

    Raises ``ValueError`` on unknown clock names so config validation has
    one place that knows the vocabulary.
    """
    if clock == "sync":
        return ()
    if clock == "poisson":
        return (float(activation_rate), int(id_div))
    raise ValueError(f"unknown clock model {clock!r}; use 'sync' or 'poisson'")


def activation_probability(clock: Tuple) -> float:
    """Static per-round activation probability ``1 - exp(-rate)``.

    Returns 1.0 for the synchronous clock. Computed with ``math.exp`` at
    trace time — the probability is a Python float baked into the program,
    never a traced value.
    """
    if not clock:
        return 1.0
    rate = float(clock[0])
    return 1.0 - math.exp(-rate)


def activation_mask(round_key: jax.Array, clock: Tuple,
                    gids: jax.Array) -> jax.Array:
    """Bool[rows] — which rows' clocks ticked this round.

    ``round_key`` is the per-round key (already ``fold_in(base_key,
    round)``); the CLOCK_FOLD domain separation happens here. ``gids``
    are *global* row ids, so the mask is sharding-invariant. Callers must
    only invoke this under a poisson spec — the sync path must not trace
    any of this (the goldens pin the pre-async program text).
    """
    assert clock, "activation_mask called under the synchronous clock"
    if clock[0] == "prob":
        # traced-probability spec (sweep lanes): p is already the
        # host-rounded float32 activation probability — use it verbatim
        # so the draw threshold matches the static-rate program bitwise
        p_arr = jnp.asarray(clock[1], jnp.float32)
        id_div = int(clock[2])
    else:
        p_arr = jnp.float32(activation_probability(clock))
        id_div = int(clock[1])
    ids = gids if id_div == 1 else gids // jnp.int32(id_div)
    # drop_mask draws u32 < p·2^32 — reused here as a Bernoulli(p)
    # sampler where "dropped" means "active"
    return drop_mask(
        jax.random.fold_in(round_key, CLOCK_FOLD), p_arr, ids
    )
