"""Asynchronous execution model: deterministic Poisson activation clocks.

The engine's rounds are bulk-synchronous; this package turns "which nodes
act this round" into a scenario axis. A *clock spec* is a small static
tuple threaded through the round cores exactly like the fault engine's
loss windows: ``()`` means the synchronous clock (every node acts, the
traced program is byte-identical to the pre-async engine), and
``(rate, id_div)`` means independent Poisson clocks thinned to rounds —
each round a node is active with probability ``1 - exp(-rate)``, drawn
counter-based from the run PRNG so trajectories are seed-deterministic
and sharding-invariant.
"""

from gossipprotocol_tpu.async_.clock import (
    CLOCK_FOLD,
    activation_mask,
    activation_probability,
    clock_spec,
)

__all__ = [
    "CLOCK_FOLD",
    "activation_mask",
    "activation_probability",
    "clock_spec",
]
