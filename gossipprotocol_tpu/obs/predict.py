"""Analytic round prediction from the topology's spectrum.

Push-sum averaging contracts the consensus error by γ = |λ₂(W)| per
application of the mixing matrix, so the round count to residual ``tol``
obeys the classical bound (Kempe et al.; in the tight spectral form of
the recent gossip-convergence analyses, e.g. arXiv:2507.16601)

    T(tol) ≲ (ln n + ln(1/tol)) / (−ln γ)

:func:`predict_rounds` evaluates that bound for this run's configuration
before anything is compiled: γ comes from ``cfg.accel_lambda`` when the
user supplied a spectral bound, otherwise from the same host
power-iteration the Chebyshev acceleration uses
(:func:`~gossipprotocol_tpu.protocols.accel.estimate_gamma` — O(iters·E)
numpy on the CSR). The streak/plateau tail the predicates append rides
on top as ``+ streak_target + 1``.

Gossip (rumor spreading with a hit threshold) has no contraction rate;
its prediction is an explicitly-labelled heuristic — O(log n) spread plus
one expected hit per node per round until the threshold — kept so the
budget machinery and predicted-vs-actual report work uniformly.

``round_budget="auto"`` turns the prediction into an enforced budget of
``BUDGET_FACTOR × predicted`` rounds: a run that overshoots the analytic
bound by that factor is not converging at the predicted rate and exits
with a structured ``over_budget`` record instead of grinding to
``max_rounds``.

The power iteration is gated by edge count (``PREDICT_EDGE_CAP``,
overridable via ``$GOSSIP_TPU_PREDICT_EDGE_CAP``): past the cap
:func:`maybe_predict_rounds` declines unless the caller *requires* a
prediction (``round_budget="auto"``), in which case it pays the cost —
an explicit request beats a silent no-budget run.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, Optional

BUDGET_FACTOR = 8
PREDICT_EDGE_CAP_DEFAULT = 5_000_000
# power iteration sweeps: small graphs have the tiniest eigengaps between
# λ₂ and λ₃ (a line's are O(1/n²) apart), so give them many iterations;
# the budget is ~constant host work either way (iters · E ≈ 4e7)
PREDICT_ITERS_BUDGET = 40_000_000
PREDICT_ITERS_MIN = 200
PREDICT_ITERS_MAX = 5_000


def predict_edge_cap() -> int:
    return int(os.environ.get("GOSSIP_TPU_PREDICT_EDGE_CAP",
                              PREDICT_EDGE_CAP_DEFAULT))


def _num_edges(topo) -> int:
    if topo.implicit_full:
        # K_n is handled analytically (estimate_gamma returns 0.0) — the
        # cap gate should never refuse it
        return 0
    return int(topo.num_directed_edges)


def _estimate_gamma(topo, cfg) -> float:
    if cfg.accel_lambda is not None:
        return float(cfg.accel_lambda)
    from gossipprotocol_tpu.protocols.accel import estimate_gamma

    edges = max(_num_edges(topo), 1)
    iters = max(PREDICT_ITERS_MIN,
                min(PREDICT_ITERS_MAX, PREDICT_ITERS_BUDGET // edges))
    return estimate_gamma(topo, iters=iters)


def predict_rounds(topo, cfg) -> Dict[str, Any]:
    """Predicted round count + auto budget for this (topology, config).

    Returns a json-able dict (it goes verbatim into ``events.jsonl`` and
    the run manifest): model name, γ and spectral gap, effective
    tolerance, ``predicted_rounds``, and ``budget_rounds`` =
    ``BUDGET_FACTOR × predicted`` clamped to ``cfg.max_rounds``.
    """
    n = max(int(topo.num_nodes), 2)
    edges = _num_edges(topo)
    doc: Dict[str, Any] = {
        "num_nodes": n,
        "num_edges": edges,
        "budget_factor": BUDGET_FACTOR,
        "clock": getattr(cfg, "clock", "sync"),
    }
    # poisson clock: each engine round only a Bernoulli(p) subset of
    # senders fires (p = 1 − e^{−rate}, the thinned-process activation),
    # so one *synchronous-equivalent* contraction step takes ~1/p rounds
    # — the classical continuous-time slowdown (arXiv:2011.02379). Sync
    # keeps the factor at exactly 1 (bitwise-unchanged prediction doc
    # modulo the new clock fields).
    slowdown = 1.0
    if getattr(cfg, "clock", "sync") == "poisson":
        from gossipprotocol_tpu.async_ import activation_probability, clock_spec

        p = activation_probability(
            clock_spec("poisson", cfg.activation_rate))
        doc["activation_rate"] = float(cfg.activation_rate)
        doc["activation_probability"] = round(p, 12)
        slowdown = 1.0 / p
    if cfg.algorithm == "gossip":
        # heuristic, not a bound: O(log n) spread (push-only rumor needs
        # ~log2 n + ln n rounds on an expander), then ~1 hit per node per
        # round until the threshold-th hit lands
        predicted = math.ceil(math.log2(n) + math.log(n)) + int(cfg.threshold)
        doc.update(model="gossip-heuristic", confidence="heuristic",
                   gamma=None, spectral_gap=None, tol=None)
    else:
        gamma = min(max(_estimate_gamma(topo, cfg), 0.0), 1.0 - 1e-12)
        tol_eff = float(cfg.tol if cfg.predicate == "global" else cfg.eps)
        if gamma <= 0.0:
            mixing = 1  # K_n: one W application mixes completely
        else:
            mixing = math.ceil(
                (math.log(n) + math.log(1.0 / tol_eff)) / -math.log(gamma))
        # the predicates append a confirmation tail on top of mixing:
        # streak_target small-delta rounds (delta) / in-tol rounds (global),
        # plus the round that first crosses
        predicted = mixing + int(cfg.streak_target) + 1
        doc.update(model="spectral-pushsum", confidence="analytic",
                   gamma=round(gamma, 12),
                   spectral_gap=round(1.0 - gamma, 12), tol=tol_eff)
    if slowdown != 1.0:
        predicted = math.ceil(predicted * slowdown)
    if getattr(cfg, "workload", "avg") in ("sgp", "gala"):
        # learning workloads stop on consensus AND a loss plateau; the
        # spectral bound only covers the mixing part, so the prediction
        # is a lower bound — downgraded so the anomaly engine's
        # round-blowout rule (analytic-only) never fires on a healthy
        # training run
        doc["confidence"] = "heuristic"
    predicted = max(1, int(predicted))
    doc["predicted_rounds"] = predicted
    doc["budget_rounds"] = int(
        min(cfg.max_rounds, predicted * BUDGET_FACTOR))
    return doc


def maybe_predict_rounds(topo, cfg, required: bool = False
                         ) -> Optional[Dict[str, Any]]:
    """:func:`predict_rounds`, declined (None) when the power iteration
    would be too expensive — unless the caller requires a prediction
    (``round_budget="auto"``), which overrides the cap. Gossip's
    heuristic needs no spectra, so the cap never gates it."""
    if (not required and cfg.algorithm != "gossip"
            and _num_edges(topo) > predict_edge_cap()):
        return None
    if (cfg.algorithm != "gossip" and cfg.accel_lambda is None
            and hasattr(topo, "csr_slice")):
        # a streamed build has no global CSR for the host power
        # iteration; γ is only available when the user supplies the
        # spectral bound (--accel-lambda)
        return None
    return predict_rounds(topo, cfg)
