"""Zero-dependency Prometheus exposition for the serve daemon.

:class:`Registry` is a minimal metrics registry — counters, gauges, and
fixed-bucket histograms — that renders the Prometheus text exposition
format (version 0.0.4) with nothing but the stdlib, served at
``/metrics`` on the daemon's loopback HTTP surface.

:class:`FleetMetrics` is the daemon-side fold: every journal record the
supervisor appends is also :meth:`~FleetMetrics.observe`-d into the
registry, and on restart the registry is rebuilt by folding the whole
``journal.jsonl`` through the *same* code path
(:meth:`FleetMetrics.from_records`). Because every monotonic counter is
a pure function of the journal — and the journal survives SIGKILL by
construction — counter values are bitwise-preserved across a daemon
crash: the restarted daemon's ``/metrics`` renders the same totals the
dead one did.

Gauges (queue depth, worker slots) are live supervisor state, set just
before each render; they are deliberately NOT journal-derived.

:func:`parse_text_exposition` is the strict zero-dep parser the tests
(and any scraper without a Prometheus client library) use: every line
must be a well-formed HELP/TYPE/sample line of a declared family, or it
raises.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from gossipprotocol_tpu.serve.journal import TERMINAL_EVENTS

# fixed histogram buckets (seconds). Queue wait is dominated by worker
# slots freeing up (sub-second to minutes); run wall by compile + the
# round loop (seconds to an hour).
WAIT_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                30.0, 60.0, 120.0, 300.0)
RUN_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
               300.0, 600.0, 1800.0, 3600.0)

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"        # metric name
    r"(?:\{([^}]*)\})?"                    # optional {labels}
    r" (-?(?:[0-9.eE+-]+|\+Inf|-Inf|NaN))$")  # value
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v) == int(v):
        return str(int(v))
    return repr(float(v))


def _fmt_label_value(v: str) -> str:
    return (v.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_fmt_label_value(str(v))}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class Counter:
    """Monotonic counter; with ``labels``, one series per value tuple."""

    kind = "counter"

    def __init__(self, name: str, help: str,
                 labels: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labels = tuple(labels)
        self.values: Dict[Tuple[str, ...], float] = {}
        if not self.labels:
            self.values[()] = 0.0

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(str(labels[n]) for n in self.labels)
        self.values[key] = self.values.get(key, 0.0) + amount

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for key in sorted(self.values):
            lines.append(f"{self.name}{_fmt_labels(self.labels, key)} "
                         f"{_fmt_value(self.values[key])}")
        return lines


class Gauge(Counter):
    """Settable instantaneous value (live state, not journal-derived)."""

    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = tuple(str(labels[n]) for n in self.labels)
        self.values[key] = float(value)


class Histogram:
    """Fixed-bucket histogram: cumulative ``_bucket`` series + ``_sum``
    and ``_count``, the classic Prometheus layout."""

    kind = "histogram"

    def __init__(self, name: str, help: str, buckets: Sequence[float]):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * len(self.buckets)  # per-bucket, NOT cumulative
        self.inf_count = 0
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        self.sum += value
        self.count += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.inf_count += 1

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            lines.append(
                f'{self.name}_bucket{{le="{_fmt_value(b)}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {self.count}')
        lines.append(f"{self.name}_sum {_fmt_value(round(self.sum, 6))}")
        lines.append(f"{self.name}_count {self.count}")
        return lines


class Registry:
    """Ordered family registry; :meth:`render` is the /metrics body."""

    def __init__(self):
        self.families: Dict[str, Any] = {}

    def _add(self, fam):
        if not _NAME_RE.match(fam.name):
            raise ValueError(f"bad metric name {fam.name!r}")
        if fam.name in self.families:
            raise ValueError(f"duplicate metric {fam.name!r}")
        self.families[fam.name] = fam
        return fam

    def counter(self, name: str, help: str,
                labels: Sequence[str] = ()) -> Counter:
        return self._add(Counter(name, help, labels))

    def gauge(self, name: str, help: str,
              labels: Sequence[str] = ()) -> Gauge:
        return self._add(Gauge(name, help, labels))

    def histogram(self, name: str, help: str,
                  buckets: Sequence[float]) -> Histogram:
        return self._add(Histogram(name, help, buckets))

    def render(self) -> str:
        lines: List[str] = []
        for fam in self.families.values():
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------
# the strict parser (tests + client-side scraping without a library)


def parse_text_exposition(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse Prometheus text exposition into
    ``{family: {type, help, samples: [(name, labels, value)]}}``.

    Strict by design: any line that is not a well-formed HELP/TYPE line
    or a sample of an already-declared family raises ``ValueError`` with
    the offending line — the golden tests feed every rendered line
    through here.
    """
    families: Dict[str, Dict[str, Any]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            raise ValueError(f"line {lineno}: blank line in exposition")
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            if not _NAME_RE.match(name):
                raise ValueError(f"line {lineno}: bad HELP name {name!r}")
            families.setdefault(
                name, {"type": None, "help": None, "samples": []}
            )["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):]
            name, _, kind = rest.partition(" ")
            if kind not in ("counter", "gauge", "histogram", "summary",
                            "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE {kind!r}")
            families.setdefault(
                name, {"type": None, "help": None, "samples": []}
            )["type"] = kind
            continue
        if line.startswith("#"):
            raise ValueError(f"line {lineno}: unexpected comment {line!r}")
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        name, label_text, value_text = m.groups()
        fam_name = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and families.get(base, {}).get("type") == "histogram":
                fam_name = base
                break
        if fam_name not in families:
            raise ValueError(
                f"line {lineno}: sample {name!r} has no declared family")
        labels: Dict[str, str] = {}
        if label_text:
            pos = 0
            while pos < len(label_text):
                lm = _LABEL_RE.match(label_text, pos)
                if lm is None:
                    raise ValueError(
                        f"line {lineno}: unparseable labels "
                        f"{label_text!r} at offset {pos}")
                labels[lm.group(1)] = (
                    lm.group(2).replace('\\"', '"')
                    .replace("\\n", "\n").replace("\\\\", "\\"))
                pos = lm.end()
                if pos < len(label_text):
                    if label_text[pos] != ",":
                        raise ValueError(
                            f"line {lineno}: expected ',' in labels "
                            f"{label_text!r} at offset {pos}")
                    pos += 1
        if value_text == "+Inf":
            value = math.inf
        elif value_text == "-Inf":
            value = -math.inf
        else:
            value = float(value_text)
        families[fam_name]["samples"].append((name, labels, value))
    for name, fam in families.items():
        if fam["type"] is None:
            raise ValueError(f"family {name!r} has samples but no TYPE")
    return families


def check_histogram_consistency(name: str,
                                fam: Dict[str, Any]) -> None:
    """Raise unless the histogram family is internally consistent:
    cumulative bucket counts are non-decreasing in ``le``, the ``+Inf``
    bucket equals ``_count``, and every bound parses and is ordered."""
    buckets = [(labels.get("le"), v) for n, labels, v in fam["samples"]
               if n == name + "_bucket"]
    count = next(v for n, _, v in fam["samples"] if n == name + "_count")
    if not buckets:
        raise ValueError(f"{name}: no _bucket samples")
    bounds = []
    prev = None
    for le, v in buckets:
        b = math.inf if le == "+Inf" else float(le)
        bounds.append(b)
        if prev is not None and v < prev:
            raise ValueError(f"{name}: bucket counts decrease at le={le}")
        prev = v
    if bounds != sorted(bounds):
        raise ValueError(f"{name}: bucket bounds out of order")
    if not math.isinf(bounds[-1]):
        raise ValueError(f"{name}: missing +Inf bucket")
    if buckets[-1][1] != count:
        raise ValueError(
            f"{name}: +Inf bucket {buckets[-1][1]} != _count {count}")


# ---------------------------------------------------------------------
# the daemon fold: journal records -> registry


def refusal_reason_class(reason: str) -> str:
    """Collapse a refusal message into a bounded label set (labels must
    not carry unbounded cardinality like raw message text)."""
    reason = reason or ""
    if reason.startswith("queue full"):
        return "queue_full"
    if reason.startswith("over budget"):
        return "over_budget"
    if "exceeds 90% of device capacity" in reason:
        return "capacity"
    if (reason.startswith("request invalid")
            or reason.startswith("request unreadable")):
        return "invalid"
    return "other"


class FleetMetrics:
    """The serve daemon's metric fold over journal records.

    ``observe`` is called once per appended journal record (live) and
    once per replayed record (restart): identical record streams produce
    identical — bitwise — counter and histogram states, which is the
    whole SIGKILL-durability story.
    """

    def __init__(self):
        r = self.registry = Registry()
        self.accepted = r.counter(
            "gossip_requests_accepted_total",
            "Requests moved from incoming/ into the daemon's queue.")
        self.admitted = r.counter(
            "gossip_requests_admitted_total",
            "Requests that passed admission (capacity + budget).")
        self.refused = r.counter(
            "gossip_requests_refused_total",
            "Requests refused at admission, by reason class.",
            labels=("reason",))
        self.outcomes = r.counter(
            "gossip_requests_outcome_total",
            "Terminal request outcomes (plus drained pauses).",
            labels=("outcome",))
        self.retries = r.counter(
            "gossip_infra_retries_total",
            "Device-side infra failures re-queued with backoff.")
        self.backoff_s = r.counter(
            "gossip_retry_backoff_seconds_total",
            "Total backoff seconds scheduled before infra retries.")
        self.sweep_batches = r.counter(
            "gossip_sweep_batches_total",
            "Sweep batches fused from compatible queued requests.")
        self.sweep_lanes = r.counter(
            "gossip_sweep_batch_lanes_total",
            "Requests executed as sweep lanes inside a batch.")
        self.queue_depth = r.gauge(
            "gossip_queue_depth",
            "Requests pending or running right now (live state).")
        self.workers_active = r.gauge(
            "gossip_workers_active",
            "Worker subprocesses currently running (live state).")
        self.workers_max = r.gauge(
            "gossip_workers_max",
            "Configured worker-slot ceiling (--max-workers).")
        self.queue_max = r.gauge(
            "gossip_queue_max",
            "Configured backlog ceiling (--max-queue).")
        self.wait_hist = r.histogram(
            "gossip_request_queue_wait_seconds",
            "Seconds from acceptance to first worker start (or refusal).",
            WAIT_BUCKETS)
        self.run_hist = r.histogram(
            "gossip_request_run_wall_seconds",
            "Seconds from first worker start to the terminal event.",
            RUN_BUCKETS)
        self._accepted_ts: Dict[str, float] = {}
        self._started_ts: Dict[str, float] = {}
        self._waited: set = set()
        self._batch_ids: set = set()

    @classmethod
    def from_records(cls, records: List[Dict[str, Any]]) -> "FleetMetrics":
        """Rebuild the registry by folding a replayed journal — the
        restart path. Same fold as live, so same bytes."""
        m = cls()
        for rec in records:
            m.observe(rec)
        return m

    def observe(self, rec: Dict[str, Any]) -> None:
        event = rec.get("event")
        rid = rec.get("request_id")
        ts = rec.get("ts")
        if event == "accepted":
            self.accepted.inc()
            if isinstance(ts, (int, float)):
                self._accepted_ts[rid] = ts
        elif event == "admitted":
            self.admitted.inc()
        elif event == "refused":
            self.refused.inc(
                reason=refusal_reason_class(rec.get("reason", "")))
            self._observe_wait(rid, ts)
        elif event in ("started", "batched"):
            if event == "batched":
                self.sweep_lanes.inc()
                batch = rec.get("batch")
                if batch and batch not in self._batch_ids:
                    self._batch_ids.add(batch)
                    self.sweep_batches.inc()
            if rid not in self._started_ts and isinstance(
                    ts, (int, float)):
                self._started_ts[rid] = ts
            self._observe_wait(rid, ts)
        elif event == "retry":
            self.retries.inc()
            backoff = rec.get("backoff_s")
            if isinstance(backoff, (int, float)):
                self.backoff_s.inc(backoff)
        elif event == "drained":
            self.outcomes.inc(outcome="drained")
        elif event in TERMINAL_EVENTS and event != "refused":
            self.outcomes.inc(outcome=event)
            started = self._started_ts.pop(rid, None)
            if started is not None and isinstance(ts, (int, float)):
                self.run_hist.observe(round(max(0.0, ts - started), 3))

    def _observe_wait(self, rid: str, ts: Any) -> None:
        if rid in self._waited:
            return
        accepted = self._accepted_ts.get(rid)
        if accepted is None or not isinstance(ts, (int, float)):
            return
        self._waited.add(rid)
        self.wait_hist.observe(round(max(0.0, ts - accepted), 3))

    def set_live(self, *, queue_depth: int, workers_active: int,
                 workers_max: int, queue_max: int) -> None:
        self.queue_depth.set(queue_depth)
        self.workers_active.set(workers_active)
        self.workers_max.set(workers_max)
        self.queue_max.set(queue_max)

    def render(self) -> str:
        return self.registry.render()
