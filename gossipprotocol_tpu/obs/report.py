"""Human-readable rendering of a telemetry dir.

``python -m gossipprotocol_tpu report DIR`` reads what a ``--telemetry-dir``
run left behind — ``run.json``, ``events.jsonl`` — and prints the
summary you'd want before trusting (or debugging) the run: where the wall
time went, what the counters totalled, how convergence progressed, and
any anomaly the records can prove.

Exit codes: 0 on success, 2 when DIR is missing/empty or the records
carry a schema major version newer than this reader (absent ``"v"``
means version 1 — see :mod:`gossipprotocol_tpu.utils.metrics`).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, TextIO

from gossipprotocol_tpu.utils.metrics import SCHEMA_VERSION

_SPARK = "▁▂▃▄▅▆▇█"


class ReportError(Exception):
    """Unreadable telemetry dir / incompatible schema — exit code 2."""


def _check_version(doc: Dict[str, Any], where: str) -> None:
    v = doc.get("v", 1)  # absent "v" IS version 1 by contract
    if not isinstance(v, int) or v > SCHEMA_VERSION:
        raise ReportError(
            f"{where} has schema version {v!r}, but this reader understands "
            f"up to {SCHEMA_VERSION}. Upgrade gossipprotocol_tpu to read it."
        )


def load_telemetry_dir(path: str) -> Dict[str, Any]:
    """Read ``run.json`` + ``events.jsonl``; either may be absent (a run
    killed before close still leaves partial events), both absent is an
    error."""
    manifest: Optional[Dict[str, Any]] = None
    events: List[Dict[str, Any]] = []
    mpath = os.path.join(path, "run.json")
    epath = os.path.join(path, "events.jsonl")
    if os.path.isfile(mpath):
        with open(mpath) as fh:
            manifest = json.load(fh)
        _check_version(manifest, mpath)
    if os.path.isfile(epath):
        with open(epath) as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn final line from a killed run
                if i == 0:
                    _check_version(rec, epath)
                events.append(rec)
    if manifest is None and not events:
        raise ReportError(
            f"no telemetry found under {path!r} (expected run.json and/or "
            "events.jsonl — was the run launched with --telemetry-dir?)"
        )
    return {"manifest": manifest, "events": events}


def sparkline(values: List[float], width: int = 40) -> str:
    """Map a series onto ▁..█; downsamples to ``width`` by striding."""
    if not values:
        return ""
    if len(values) > width:
        stride = len(values) / width
        values = [values[int(i * stride)] for i in range(width)]
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _SPARK[-1] * len(values) if hi > 0 else _SPARK[0] * len(values)
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / span * (len(_SPARK) - 1)))]
        for v in values
    )


def _phases_from_events(events: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Fallback rollup when run.json never landed (crashed run)."""
    out: Dict[str, Dict[str, float]] = {}
    for rec in events:
        if rec.get("kind") != "span" or rec.get("depth", 0) != 0:
            continue
        agg = out.setdefault(rec["name"], {"count": 0, "total_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += rec.get("dur_s", 0.0)
    return out


def _wall_from_events(events: List[Dict[str, Any]]) -> Optional[float]:
    for rec in reversed(events):
        if rec.get("kind") == "end":
            return rec.get("wall_s")
    last = 0.0
    for rec in events:
        if "start_s" in rec:
            last = max(last, rec["start_s"] + rec.get("dur_s", 0.0))
    return last or None


def _metric_recs(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [r["rec"] for r in events if r.get("kind") == "metric" and "rec" in r]


def anomaly_flags(manifest: Optional[Dict[str, Any]],
                  metrics: List[Dict[str, Any]]) -> List[str]:
    flags: List[str] = []
    result = (manifest or {}).get("result")
    if result is not None and not result.get("converged", True):
        flags.append("DID NOT CONVERGE within the round budget")
    if any(r.get("stalled") for r in metrics):
        flags.append("gossip STALLED (live spreaders exhausted before quorum)")
    peak_underflow = max((r.get("w_underflow", 0) or 0 for r in metrics),
                        default=0)
    if peak_underflow:
        flags.append(
            f"push-sum w-underflow: up to {peak_underflow} alive rows hit "
            "w == 0 (dry-spell wall — consider f64)"
        )
    counters = (manifest or {}).get("counters")
    if counters and counters.get("dropped", 0) > 0:
        flags.append(f"{counters['dropped']} messages dropped by link loss")
    drift = (manifest or {}).get("max_mass_drift_ulps")
    wdrift = (manifest or {}).get("max_w_drift_ulps")
    if drift is not None and max(drift, wdrift or 0.0) > 64.0:
        flags.append(
            f"push-sum mass drift up to {max(drift, wdrift or 0.0):.0f} ULPs "
            "(large for the dtype — check loss windows / dtype choice)"
        )
    if manifest is None:
        flags.append("run.json missing: run likely crashed before finishing")
    return flags


def render(data: Dict[str, Any], out: TextIO) -> None:
    manifest = data["manifest"]
    events = data["events"]
    metrics = _metric_recs(events)

    # header -------------------------------------------------------------
    if manifest is not None:
        cfg = manifest.get("config", {})
        topo = manifest.get("topology", {})
        out.write(
            f"run: {cfg.get('algorithm', '?')} on {topo.get('kind', '?')}"
            f"-{topo.get('num_nodes', '?')}  "
            f"[{manifest.get('backend', '?')} x{manifest.get('num_devices', '?')}, "
            f"gossipprotocol_tpu {manifest.get('package_version', '?')}, "
            f"jax {manifest.get('jax_version', '?')}]\n"
        )
        if manifest.get("resume"):
            r = manifest["resume"]
            out.write(f"resumed: from {r.get('from')} at round {r.get('round')}\n")
        result = manifest.get("result")
        if result is not None:
            err = result.get("estimate_error")
            out.write(
                f"result: {'converged' if result.get('converged') else 'NOT converged'}"
                f" after {result.get('rounds')} rounds, "
                f"{result.get('wall_ms', 0.0):.1f} ms run"
                f" + {result.get('compile_ms', 0.0):.1f} ms compile"
                + (f", estimate error {err:.3e}" if err is not None else "")
                + "\n"
            )

    # phase table --------------------------------------------------------
    phases = (manifest or {}).get("phases") or _phases_from_events(events)
    wall = (manifest or {}).get("wall_s") or _wall_from_events(events)
    if phases:
        out.write("\nphases (host wall time):\n")
        rows = sorted(phases.items(), key=lambda kv: -kv[1]["total_s"])
        namew = max(len(n) for n, _ in rows)
        covered = 0.0
        for name, agg in rows:
            covered += agg["total_s"]
            pct = (100.0 * agg["total_s"] / wall) if wall else 0.0
            out.write(
                f"  {name:<{namew}}  {agg['total_s']:>9.3f} s"
                f"  x{int(agg['count']):<5d} {pct:5.1f}%\n"
            )
        if wall:
            out.write(
                f"  {'(total)':<{namew}}  {covered:>9.3f} s of "
                f"{wall:.3f} s wall ({100.0 * covered / wall:.1f}% covered)\n"
            )

    # counters -----------------------------------------------------------
    counters = (manifest or {}).get("counters")
    if counters:
        out.write(
            f"\nmessages: sent={counters.get('sent', 0)}"
            f" delivered={counters.get('delivered', 0)}"
            f" dropped={counters.get('dropped', 0)}\n"
        )
        drift = manifest.get("max_mass_drift_ulps")
        # SGP injects mass by design (the gradient step), so a conservation
        # claim would be meaningless there — the driver never measures it
        if drift is not None and (
            manifest.get("config", {}).get("workload", "avg") != "sgp"
        ):
            out.write(
                f"push-sum mass drift: |Σs| ≤ {drift:g} ULPs,"
                f" |Σw − n| ≤ {manifest.get('max_w_drift_ulps', 0.0):g} ULPs\n"
            )

    # convergence sparkline ----------------------------------------------
    if metrics:
        frac = [
            (r.get("converged", 0) / r["alive"]) if r.get("alive") else 0.0
            for r in metrics
        ]
        first, last = metrics[0].get("round", "?"), metrics[-1].get("round", "?")
        out.write(
            f"\nconvergence (fraction of alive nodes, rounds {first}..{last}):\n"
            f"  {sparkline(frac)}  {frac[-1] * 100:.1f}% final\n"
        )

    # train-loss sparkline (SGP runs record a "train_loss" per chunk) -----
    losses = [
        r["train_loss"] for r in metrics
        if isinstance(r.get("train_loss"), (int, float))
        and r["train_loss"] == r["train_loss"]  # drop NaN
        and r["train_loss"] != float("inf")  # drop the pre-round ∞ sentinel
    ]
    if losses:
        out.write(
            f"\ntrain loss (mean over alive nodes):\n"
            f"  {sparkline(losses)}  {losses[-1]:.3e} final\n"
        )

    # anomalies ----------------------------------------------------------
    flags = anomaly_flags(manifest, metrics)
    if flags:
        out.write("\nanomalies:\n")
        for f in flags:
            out.write(f"  ! {f}\n")
    else:
        out.write("\nanomalies: none\n")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m gossipprotocol_tpu report TELEMETRY_DIR",
              file=sys.stderr if not argv else sys.stdout)
        return 0 if argv else 2
    path = argv[0]
    if not os.path.isdir(path):
        print(f"report: {path!r} is not a directory", file=sys.stderr)
        return 2
    try:
        data = load_telemetry_dir(path)
    except ReportError as e:
        print(f"report: {e}", file=sys.stderr)
        return 2
    render(data, sys.stdout)
    return 0
