"""Human-readable rendering of a telemetry dir.

``python -m gossipprotocol_tpu report DIR`` reads what a ``--telemetry-dir``
run left behind — ``run.json``, ``events.jsonl``, ``trace.jsonl`` — and
prints the summary you'd want before trusting (or debugging) the run:
where the wall time went, what the counters totalled, how convergence
progressed round by round, how the analytic prediction compared to
reality, and any anomaly the records can prove
(:mod:`gossipprotocol_tpu.obs.anomaly`).

A dir with events but no manifest (killed run, or one still running) gets
a *partial* report under a ``run incomplete`` banner — partial telemetry
is an answer, not an error.

``report DIR --compare BASELINE_DIR [--threshold F]`` additionally diffs
the run against a baseline telemetry dir: rounds, time-to-convergence,
and per-phase wall time, exiting 3 when either regresses beyond the
threshold (default 0.2 = 20%).

Exit codes: 0 on success (including partial reports), 2 when DIR is
missing/empty or the records carry a schema major version newer than
this reader (absent ``"v"`` means version 1 — see
:mod:`gossipprotocol_tpu.utils.metrics`), 3 when ``--compare`` found a
regression beyond the threshold.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, TextIO, Tuple

from gossipprotocol_tpu.obs.anomaly import anomaly_flags  # re-export
from gossipprotocol_tpu.obs.resources import load_resources
from gossipprotocol_tpu.obs.trace import load_trace
from gossipprotocol_tpu.utils.metrics import SCHEMA_VERSION

__all__ = ["ReportError", "load_telemetry_dir", "sparkline",
           "anomaly_flags", "render", "compare", "main"]

_SPARK = "▁▂▃▄▅▆▇█"

# --compare: relative slowdown beyond this fraction is a regression
COMPARE_THRESHOLD_DEFAULT = 0.2


class ReportError(Exception):
    """Unreadable telemetry dir / incompatible schema — exit code 2."""


def _check_version(doc: Dict[str, Any], where: str) -> None:
    v = doc.get("v", 1)  # absent "v" IS version 1 by contract
    if not isinstance(v, int) or v > SCHEMA_VERSION:
        raise ReportError(
            f"{where} has schema version {v!r}, but this reader understands "
            f"up to {SCHEMA_VERSION}. Upgrade gossipprotocol_tpu to read it."
        )


def load_telemetry_dir(path: str) -> Dict[str, Any]:
    """Read ``run.json`` + ``events.jsonl`` + ``trace.jsonl``; any may be
    absent (a run killed before close still leaves partial events and
    trace rows), all absent is an error."""
    manifest: Optional[Dict[str, Any]] = None
    events: List[Dict[str, Any]] = []
    mpath = os.path.join(path, "run.json")
    epath = os.path.join(path, "events.jsonl")
    if os.path.isfile(mpath):
        with open(mpath) as fh:
            manifest = json.load(fh)
        _check_version(manifest, mpath)
    if os.path.isfile(epath):
        with open(epath) as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn final line from a killed run
                if i == 0:
                    _check_version(rec, epath)
                events.append(rec)
    trace = load_trace(os.path.join(path, "trace.jsonl"))
    resources = load_resources(path)
    if manifest is None and not events and not trace and resources is None:
        raise ReportError(
            f"no telemetry found under {path!r} (expected run.json and/or "
            "events.jsonl — was the run launched with --telemetry-dir?)"
        )
    return {"manifest": manifest, "events": events, "trace": trace,
            "resources": resources}


def sparkline(values: List[float], width: int = 40) -> str:
    """Map a series onto ▁..█; downsamples to ``width`` by striding."""
    if not values:
        return ""
    if len(values) > width:
        stride = len(values) / width
        values = [values[int(i * stride)] for i in range(width)]
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _SPARK[-1] * len(values) if hi > 0 else _SPARK[0] * len(values)
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / span * (len(_SPARK) - 1)))]
        for v in values
    )


def _phases_from_events(events: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Fallback rollup when run.json never landed (crashed run)."""
    out: Dict[str, Dict[str, float]] = {}
    for rec in events:
        if rec.get("kind") != "span" or rec.get("depth", 0) != 0:
            continue
        agg = out.setdefault(rec["name"], {"count": 0, "total_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += rec.get("dur_s", 0.0)
    return out


def _wall_from_events(events: List[Dict[str, Any]]) -> Optional[float]:
    for rec in reversed(events):
        if rec.get("kind") == "end":
            return rec.get("wall_s")
    last = 0.0
    for rec in events:
        if "start_s" in rec:
            last = max(last, rec["start_s"] + rec.get("dur_s", 0.0))
    return last or None


def _metric_recs(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [r["rec"] for r in events if r.get("kind") == "metric" and "rec" in r]


def _fmt_bytes(n: Any) -> str:
    if not isinstance(n, (int, float)):
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return "?"


def _render_resources(data: Dict[str, Any], manifest, out: TextIO) -> None:
    res = data.get("resources")
    balance = (manifest or {}).get("shard_balance")
    if not res and not balance:
        return
    out.write("\nresources:\n")
    if res:
        host = res.get("host") or {}
        if host.get("peak_rss_bytes") is not None:
            out.write(
                f"  host RSS: {_fmt_bytes(host.get('rss_bytes'))} current, "
                f"{_fmt_bytes(host['peak_rss_bytes'])} peak\n")
        for prog in res.get("programs") or []:
            cost = prog.get("cost") or {}
            mem = prog.get("memory") or {}
            parts = []
            if cost.get("flops") is not None:
                parts.append(f"{cost['flops']:.3e} flops")
            if cost.get("bytes accessed") is not None:
                parts.append(f"{_fmt_bytes(cost['bytes accessed'])} accessed")
            if mem.get("argument_size_in_bytes") is not None:
                parts.append(
                    f"args {_fmt_bytes(mem['argument_size_in_bytes'])}")
            if mem.get("temp_size_in_bytes") is not None:
                parts.append(
                    f"temp {_fmt_bytes(mem['temp_size_in_bytes'])}")
            if mem.get("output_size_in_bytes") is not None:
                parts.append(
                    f"out {_fmt_bytes(mem['output_size_in_bytes'])}")
            label = prog.get("label", "?")
            # execution-shape tag, e.g. `chunk [2-shard, pallas, K=16,
            # bf16]`: the shard count subsumes the "sharded" engine word
            shards = prog.get("num_shards")
            k = prog.get("rounds_per_kernel")
            hs = prog.get("hub_split")
            tags = [t for t in (
                f"{shards}-shard" if shards else prog.get("engine"),
                prog.get("delivery"),
                f"K={k}" if k else None,
                prog.get("payload_wire"),
                f"split={hs}" if hs else None,
            ) if t]
            if tags:
                label = f"{label} [{', '.join(tags)}]"
            out.write(f"  program {label}: "
                      + (", ".join(parts) if parts else "(no analysis)")
                      + "\n")
        notes = res.get("notes") or {}
        if notes.get("exchange_bytes_per_round") is not None:
            out.write(
                f"  edge-share exchange: "
                f"{_fmt_bytes(notes['exchange_bytes_per_round'])}/round\n")
        if notes.get("routed_table_bytes") is not None:
            out.write(
                f"  routed tables: "
                f"{_fmt_bytes(notes['routed_table_bytes'])}\n")
    if balance:
        skew = balance.get("sent_skew_max_over_mean")
        out.write(
            f"  shard balance ({balance.get('num_shards', '?')} shards): "
            f"sent={balance.get('sent')}"
            + (f"  skew {skew:.3f}x max/mean" if isinstance(skew, float)
               else "")
            + "\n")


def render(data: Dict[str, Any], out: TextIO) -> None:
    manifest = data["manifest"]
    events = data["events"]
    trace = data.get("trace") or []
    metrics = _metric_recs(events)

    # incomplete banner -------------------------------------------------
    if manifest is None:
        out.write(
            "*** run incomplete: no run.json yet (crashed or still "
            "running) — partial report from events/trace ***\n"
        )

    # header -------------------------------------------------------------
    if manifest is not None:
        cfg = manifest.get("config", {})
        topo = manifest.get("topology", {})
        out.write(
            f"run: {cfg.get('algorithm', '?')} on {topo.get('kind', '?')}"
            f"-{topo.get('num_nodes', '?')}  "
            f"[{manifest.get('backend', '?')} x{manifest.get('num_devices', '?')}, "
            f"gossipprotocol_tpu {manifest.get('package_version', '?')}, "
            f"jax {manifest.get('jax_version', '?')}]\n"
        )
        if manifest.get("request_id"):
            adm = manifest.get("admission") or {}
            line = f"request: {manifest['request_id']} (daemon-executed"
            if adm.get("verdict"):
                line += f", admission {adm['verdict']}"
            if adm.get("queue_depth") is not None:
                line += f", queue depth {adm['queue_depth']}"
            out.write(line + ")\n")
        do = manifest.get("daemon_outcome")
        if do:
            out.write(f"daemon outcome: {do.get('event')} — "
                      f"{do.get('reason')}\n")
        for lc in manifest.get("lifecycle") or []:
            # daemon-side request timeline (serve/lifecycle.py stamp):
            # phase durations accepted->admitted->... -> outcome
            steps = " -> ".join(
                f"{p.get('phase')} {p.get('dur_s', 0.0):.2f}s"
                for p in lc.get("phases") or [])
            line = f"lifecycle: {lc.get('request_id')}  {steps}"
            line += f" -> {lc.get('outcome')}"
            if lc.get("retries"):
                line += f"  ({lc['retries']} infra retr"
                line += "y)" if lc["retries"] == 1 else "ies)"
            out.write(line + "\n")
        if manifest.get("resume"):
            r = manifest["resume"]
            out.write(f"resumed: from {r.get('from')} at round {r.get('round')}\n")
        result = manifest.get("result")
        if result is not None:
            err = result.get("estimate_error")
            out.write(
                f"result: {'converged' if result.get('converged') else 'NOT converged'}"
                f" after {result.get('rounds')} rounds, "
                f"{result.get('wall_ms', 0.0):.1f} ms run"
                f" + {result.get('compile_ms', 0.0):.1f} ms compile"
                + (f", estimate error {err:.3e}" if err is not None else "")
                + ("  [drained]" if result.get("stopped") == "drain"
                   else "")
                + "\n"
            )

    # hub split ----------------------------------------------------------
    hs = (manifest or {}).get("hub_split")
    if hs:
        out.write(
            f"hub split: {hs.get('classes', '?')} classes -> "
            f"{hs.get('subclasses', '?')} sub-classes "
            f"(max degree {hs.get('max_degree', '?')})\n"
        )

    # prediction ---------------------------------------------------------
    pred = (manifest or {}).get("prediction")
    if pred:
        gamma = pred.get("gamma")
        gpart = f", gamma={gamma:.6f}" if isinstance(gamma, float) else ""
        out.write(
            f"prediction: {pred.get('model', '?')}"
            f" ({pred.get('confidence', '?')}{gpart})"
            f" predicted {pred.get('predicted_rounds', '?')} rounds,"
            f" budget {pred.get('budget_rounds', '?')}"
        )
        if pred.get("actual_rounds") is not None:
            ratio = pred.get("actual_over_predicted")
            out.write(
                f"; actual {pred['actual_rounds']}"
                + (f" ({ratio:.2f}x predicted)" if ratio is not None else "")
            )
        out.write("\n")

    # phase table --------------------------------------------------------
    phases = (manifest or {}).get("phases") or _phases_from_events(events)
    wall = (manifest or {}).get("wall_s") or _wall_from_events(events)
    if phases:
        out.write("\nphases (host wall time):\n")
        rows = sorted(phases.items(), key=lambda kv: -kv[1]["total_s"])
        namew = max(len(n) for n, _ in rows)
        covered = 0.0
        for name, agg in rows:
            covered += agg["total_s"]
            pct = (100.0 * agg["total_s"] / wall) if wall else 0.0
            out.write(
                f"  {name:<{namew}}  {agg['total_s']:>9.3f} s"
                f"  x{int(agg['count']):<5d} {pct:5.1f}%\n"
            )
        if wall:
            out.write(
                f"  {'(total)':<{namew}}  {covered:>9.3f} s of "
                f"{wall:.3f} s wall ({100.0 * covered / wall:.1f}% covered)\n"
            )

    # counters -----------------------------------------------------------
    counters = (manifest or {}).get("counters")
    if counters:
        out.write(
            f"\nmessages: sent={counters.get('sent', 0)}"
            f" delivered={counters.get('delivered', 0)}"
            f" dropped={counters.get('dropped', 0)}\n"
        )
        drift = manifest.get("max_mass_drift_ulps")
        # SGP/GALA inject mass by design (the gradient step), so a
        # conservation claim would be meaningless there — the driver
        # never measures it
        if drift is not None and (
            manifest.get("config", {}).get("workload", "avg")
            not in ("sgp", "gala")
        ):
            out.write(
                f"push-sum mass drift: |Σs| ≤ {drift:g} ULPs,"
                f" |Σw − n| ≤ {manifest.get('max_w_drift_ulps', 0.0):g} ULPs\n"
            )

    # sweep rollup --------------------------------------------------------
    sweep = (manifest or {}).get("sweep")
    if sweep:
        frac = sweep.get("converged_fraction")
        out.write(
            f"\nsweep: {sweep.get('lanes', '?')} lanes, "
            f"{sweep.get('converged_lanes', '?')} converged"
            + (f" ({frac:.0%})" if isinstance(frac, (int, float)) else "")
            + f", rounds p50 {sweep.get('rounds_p50', 0):.0f}"
            f" / p95 {sweep.get('rounds_p95', 0):.0f}"
            f" / max {sweep.get('rounds_max', '?')}"
            + ("  OVER BUDGET" if sweep.get("over_budget") else "")
            + "\n"
        )
        spec = sweep.get("spec") or {}
        axes = spec.get("axes")
        if axes:
            out.write(f"  axes ({spec.get('mode', 'product')}): "
                      + ", ".join(f"{k}[{len(v)}]" for k, v in axes.items())
                      + "\n")
        lanes = sweep.get("per_lane") or []
        shown = lanes[:16]
        for lr in shown:
            over = lr.get("overrides") or {}
            desc = ", ".join(f"{k}={v}" for k, v in over.items()) or "-"
            out.write(
                f"  lane {lr.get('lane', '?'):>3}  {desc:<28} "
                f"{'converged' if lr.get('converged') else 'NOT converged'}"
                f" @ {lr.get('rounds', '?')} rounds\n")
        if len(lanes) > len(shown):
            out.write(f"  ... {len(lanes) - len(shown)} more lanes "
                      "(see run.json / run_index.jsonl)\n")

    # resource observatory -----------------------------------------------
    _render_resources(data, manifest, out)

    # convergence sparkline ----------------------------------------------
    if metrics:
        frac = [
            (r.get("converged", 0) / r["alive"]) if r.get("alive") else 0.0
            for r in metrics if "alive" in r or "converged" in r
        ]
        chunked = [r for r in metrics if "round" in r]
        if frac and chunked:
            first, last = chunked[0].get("round", "?"), chunked[-1].get("round", "?")
            out.write(
                f"\nconvergence (fraction of alive nodes, rounds {first}..{last}):\n"
                f"  {sparkline(frac)}  {frac[-1] * 100:.1f}% final\n"
            )

    # per-round residual trace -------------------------------------------
    residuals = [
        (r["round"], r["residual"]) for r in trace
        if isinstance(r.get("residual"), (int, float))
        and r["residual"] == r["residual"]
    ]
    if residuals:
        vals = [v for _, v in residuals]
        tsum = (manifest or {}).get("trace") or {}
        out.write(
            f"\nresidual trace (per-round, rounds {residuals[0][0]}.."
            f"{residuals[-1][0]}, {len(residuals)} rows"
            + (f", stride {tsum['stride']}" if tsum.get("stride") else "")
            + f"):\n  {sparkline(vals)}  {vals[-1]:.3e} final\n"
        )

    # train-loss sparkline (SGP runs record a "train_loss" per chunk) -----
    losses = [
        r["train_loss"] for r in metrics
        if isinstance(r.get("train_loss"), (int, float))
        and r["train_loss"] == r["train_loss"]  # drop NaN
        and r["train_loss"] != float("inf")  # drop the pre-round ∞ sentinel
    ]
    if losses:
        out.write(
            f"\ntrain loss (mean over alive nodes):\n"
            f"  {sparkline(losses)}  {losses[-1]:.3e} final\n"
        )

    # topology-schedule events (events/) ---------------------------------
    churn_recs = [r for r in metrics if r.get("event") == "churn"]
    if churn_recs:
        added = sum(int(r.get("edges_added", 0)) for r in churn_recs)
        removed = sum(int(r.get("edges_removed", 0)) for r in churn_recs)
        swapped = sum(int(r.get("edges_swapped", 0)) for r in churn_recs)
        skipped = sum(int(r.get("edges_skipped", 0)) for r in churn_recs)
        gen = sum(1 for r in churn_recs if r.get("generated"))
        out.write(
            f"\nchurn applied: {len(churn_recs)} event round(s)"
            f" ({gen} generated) — edges +{added} -{removed}"
            f" ~{swapped} swapped, {skipped} skipped\n"
        )

    # data-fault sentinel (value faults, trips, quarantines) --------------
    vf_recs = [r for r in metrics if r.get("event") == "value_fault"]
    for r in vf_recs:
        out.write(
            f"\nvalue fault injected: {int(r.get('nodes', 0))} node(s) at "
            f"round {r.get('round', '?')} (model {r.get('model', '?')}, "
            f"rate {r.get('rate', '?')})\n"
        )
    trip_recs = [r for r in metrics if r.get("event") == "sentinel_trip"]
    for r in trip_recs:
        out.write(
            f"sentinel trip: {r.get('cause', '?')} on "
            f"{int(r.get('nodes', 0))} node(s) at round "
            f"{r.get('round', '?')} (mode {r.get('mode', '?')})\n"
        )
    for r in metrics:
        if r.get("event") == "rollback":
            out.write(
                f"rollback: restored round {r.get('round', '?')} from "
                f"round {r.get('from_round', '?')} "
                f"({os.path.basename(str(r.get('checkpoint', '?')))})\n"
            )
    quar_recs = [r for r in metrics if r.get("event") == "quarantine"]
    for r in quar_recs:
        out.write(
            f"quarantined: {int(r.get('nodes', 0))} node(s) at round "
            f"{r.get('round', '?')} (repair {r.get('policy', '?')})\n"
        )

    # anomalies ----------------------------------------------------------
    flags = anomaly_flags(manifest, metrics, trace)
    if flags:
        out.write("\nanomalies:\n")
        for f in flags:
            out.write(f"  ! {f}\n")
    else:
        out.write("\nanomalies: none\n")


# ---------------------------------------------------------------------------
# --compare: regression diff against a baseline telemetry dir


def _run_summary(data: Dict[str, Any]) -> Dict[str, Any]:
    manifest = data.get("manifest") or {}
    result = manifest.get("result") or {}
    pred = manifest.get("prediction") or {}
    return {
        "label": (f"{manifest.get('config', {}).get('algorithm', '?')} on "
                  f"{manifest.get('topology', {}).get('kind', '?')}-"
                  f"{manifest.get('topology', {}).get('num_nodes', '?')}"),
        "converged": result.get("converged"),
        "rounds": result.get("rounds"),
        "wall_ms": result.get("wall_ms"),
        "compile_ms": result.get("compile_ms"),
        "phases": manifest.get("phases") or {},
        "ratio": pred.get("actual_over_predicted"),
    }


def _rel_delta(cur: Optional[float], base: Optional[float]) -> Optional[float]:
    if not isinstance(cur, (int, float)) or not isinstance(base, (int, float)):
        return None
    if base <= 0:
        return None
    return (cur - base) / base


def compare(data: Dict[str, Any], baseline: Dict[str, Any], out: TextIO,
            threshold: float = COMPARE_THRESHOLD_DEFAULT) -> bool:
    """Diff ``data`` against ``baseline``; returns True when rounds or
    time-to-convergence regressed beyond ``threshold`` (relative).
    Per-phase wall deltas are reported but never gate — compile and I/O
    phases are too noisy across machines to fail a build on."""
    cur, base = _run_summary(data), _run_summary(baseline)
    out.write(f"\ncompare: {cur['label']} vs baseline {base['label']}\n")
    if cur["label"] != base["label"]:
        out.write("  (warning: configs differ — deltas may be meaningless)\n")
    regressed = False
    for key, unit, gate in (("rounds", "rounds", True),
                            ("wall_ms", "ms", True),
                            ("compile_ms", "ms", False)):
        d = _rel_delta(cur[key], base[key])
        if d is None:
            continue
        mark = ""
        if gate and d > threshold:
            regressed = True
            mark = f"  REGRESSION (> {threshold:.0%} threshold)"
        out.write(
            f"  {key:<11} {cur[key]:>12.1f} vs {base[key]:>12.1f} {unit}"
            f"  ({d:+.1%}){mark}\n"
        )
    if cur["ratio"] is not None and base["ratio"] is not None:
        out.write(
            f"  {'pred ratio':<11} {cur['ratio']:>12.2f} vs "
            f"{base['ratio']:>12.2f} x  (actual/predicted rounds)\n"
        )
    shared = sorted(set(cur["phases"]) & set(base["phases"]))
    for name in shared:
        d = _rel_delta(cur["phases"][name].get("total_s"),
                       base["phases"][name].get("total_s"))
        if d is None:
            continue
        out.write(
            f"  phase {name:<16} {cur['phases'][name]['total_s']:>9.3f} vs "
            f"{base['phases'][name]['total_s']:>9.3f} s  ({d:+.1%})\n"
        )
    if regressed:
        out.write(f"compare: REGRESSION beyond {threshold:.0%} detected\n")
    else:
        out.write(f"compare: within {threshold:.0%} of baseline\n")
    return regressed


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(
            "usage: python -m gossipprotocol_tpu report TELEMETRY_DIR "
            "[--compare BASELINE_DIR] [--threshold F]",
            file=sys.stderr if not argv else sys.stdout,
        )
        return 0 if argv else 2
    # `--compare` is a mode flag; dirs are positional in order, so both
    # `report DIR --compare BASELINE` and `report --compare DIR BASELINE`
    # read as (current, baseline)
    compare_mode = False
    threshold = COMPARE_THRESHOLD_DEFAULT
    paths: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--compare":
            compare_mode = True
            i += 1
        elif a == "--threshold":
            if i + 1 >= len(argv):
                print("report: --threshold needs a value", file=sys.stderr)
                return 2
            try:
                threshold = float(argv[i + 1])
            except ValueError:
                print(f"report: bad --threshold {argv[i + 1]!r}",
                      file=sys.stderr)
                return 2
            i += 2
        else:
            paths.append(a)
            i += 1
    if not paths:
        print("report: missing TELEMETRY_DIR", file=sys.stderr)
        return 2
    path = paths[0]
    baseline_dir: Optional[str] = None
    if compare_mode or len(paths) > 1:
        if len(paths) < 2:
            print("report: --compare needs a BASELINE_DIR", file=sys.stderr)
            return 2
        baseline_dir = paths[1]
    if not os.path.isdir(path):
        print(f"report: {path!r} is not a directory", file=sys.stderr)
        return 2
    try:
        data = load_telemetry_dir(path)
    except ReportError as e:
        print(f"report: {e}", file=sys.stderr)
        return 2
    render(data, sys.stdout)
    if baseline_dir is not None:
        if not os.path.isdir(baseline_dir):
            print(f"report: baseline {baseline_dir!r} is not a directory",
                  file=sys.stderr)
            return 2
        try:
            baseline = load_telemetry_dir(baseline_dir)
        except ReportError as e:
            print(f"report: baseline: {e}", file=sys.stderr)
            return 2
        if compare(data, baseline, sys.stdout, threshold=threshold):
            return 3
    return 0
