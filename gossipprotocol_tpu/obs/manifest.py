"""Run manifest: one ``run.json`` that makes a telemetry dir self-describing.

Everything a reader needs to interpret ``events.jsonl`` / ``trace.json`` /
the metrics stream without the launching shell: the full RunConfig, the
software stack (package + jax versions, backend, device count), content
digests identifying the topology and fault schedule (the same digests the
checkpoint trajectory metadata uses, so manifests and checkpoints
cross-reference), resume lineage, the final metric, counter totals, and
the per-phase wall-time rollup.

Written once, atomically, when the run finishes (or dies — the CLI writes
it in a ``finally``); ``events.jsonl`` stays the crash-durable record.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

from gossipprotocol_tpu.utils.metrics import SCHEMA_VERSION

# runtime-only fields that either cannot serialize (callbacks, the
# telemetry hub itself) or are captured in richer form elsewhere
# ("sweep" lands as the top-level sweep rollup with per-lane records)
_SKIP_CONFIG_FIELDS = ("metrics_callback", "telemetry", "fault_schedule",
                       "fault_plan", "event_plan", "sweep",
                       "quarantine_log")


def config_doc(cfg) -> Dict[str, Any]:
    """RunConfig -> json-able dict; the fault schedule and event plan
    are folded to their normalized digests + event counts rather than
    dumped raw (large id/edge lists belong in the plan files, not every
    manifest)."""
    doc: Dict[str, Any] = {}
    for f in dataclasses.fields(cfg):
        if f.name in _SKIP_CONFIG_FIELDS:
            continue
        v = getattr(cfg, f.name)
        if f.name == "dtype":
            import jax.numpy as jnp

            v = str(jnp.dtype(v))
        doc[f.name] = v
    sched = cfg.schedule
    doc["fault_schedule"] = {
        "digest": sched.digest(),
        "kill_events": len(sched.kills),
        "revive_events": len(sched.revives),
        "loss_windows": len(sched.loss),
    }
    plan = cfg.events
    doc["event_plan"] = {
        "digest": plan.digest(),
        "add_events": len(plan.adds),
        "remove_events": len(plan.removes),
        "swap_events": len(plan.swaps),
        "churn": (None if plan.churn is None else
                  {"rate": plan.churn.rate, "model": plan.churn.model,
                   "period": int(plan.churn.period)}),
        # value-fault injections: count + the same digest the checkpoint
        # trajectory metadata pins ("none" when the plan has no faults)
        "value_fault_events": len(plan.value_faults),
        "value_faults": plan.value_fault_digest(),
    }
    return doc


def build_manifest(
    tel,
    cfg,
    topo,
    result=None,
    *,
    backend: Optional[str] = None,
    num_devices: int = 1,
    resumed_from: Optional[str] = None,
    resume_round: Optional[int] = None,
    error: Optional[str] = None,
) -> Dict[str, Any]:
    """Assemble the manifest document (pure; :func:`write_manifest` does
    the I/O). ``result`` is None when the run died before finishing —
    the manifest still lands with config + phases so the wreck is
    diagnosable."""
    import jax

    from gossipprotocol_tpu import version as pkg_version
    from gossipprotocol_tpu.utils import checkpoint as ckpt_mod

    doc: Dict[str, Any] = {
        "v": SCHEMA_VERSION,
        "kind": "run_manifest",
        "package_version": pkg_version.__version__,
        "jax_version": jax.__version__,
        "backend": backend or jax.default_backend(),
        "num_devices": int(num_devices),
        # serve/: the daemon's request id and admission verdict for runs
        # executed through the run daemon; None for direct CLI runs
        "request_id": getattr(tel, "run_id", None),
        "admission": getattr(tel, "admission", None),
        "config": config_doc(cfg),
        "topology": {
            "kind": topo.kind,
            "num_nodes": int(topo.num_nodes),
            "num_directed_edges": int(topo.num_directed_edges),
            "implicit_full": bool(topo.implicit_full),
            "fingerprint": ckpt_mod.topology_fingerprint(topo),
        },
        "resume": (
            {"from": resumed_from, "round": resume_round}
            if resumed_from is not None else None
        ),
        "phases": tel.phase_rollup(),
        "wall_s": round(tel.wall_s(), 6),
        "counters": (dict(tel.totals) if tel.counters_on else None),
        "max_mass_drift_ulps": (
            tel.max_mass_drift_ulps if tel.counters_on else None
        ),
        "max_w_drift_ulps": (
            tel.max_w_drift_ulps if tel.counters_on else None
        ),
        # obs/predict.py round prediction, updated by the driver with the
        # actual outcome (predicted_rounds / actual_rounds / over_budget)
        "prediction": getattr(tel, "prediction", None),
        # hub-splitting layout geometry on routed/pallas/megakernel runs
        # (classes / subclasses / max_degree); None on degree-regular
        # graphs, where the layout and kernels are the pre-split ones
        "hub_split": getattr(tel, "hub_split", None),
        # trace.jsonl bookkeeping (rows written, final stride, cap)
        "trace": (tel.trace_summary()
                  if hasattr(tel, "trace_summary") else None),
        # per-shard counter attribution (resource observatory): per-shard
        # sent/delivered/dropped totals + skew; None off / single-device
        "shard_balance": (tel.shard_balance()
                          if hasattr(tel, "shard_balance") else None),
        # sweep rollup (lanes, converged fraction, round percentiles,
        # per-lane records) when the run was a batched sweep; None for
        # single-trajectory runs
        "sweep": getattr(tel, "sweep", None),
        # jax.profiler trace dir when the run was profiled
        "profile_dir": getattr(tel, "profile_dir", None),
        # sibling resources.json (compiled-program cost/memory analysis,
        # RSS/device-memory samples) when the resource observatory is on
        "resources": ("resources.json"
                      if getattr(tel, "resources_on", False)
                      and tel.dir is not None else None),
    }
    # sentinel rollup: trip/quarantine counts from the run's own metric
    # records (None when the sentinel was off — healthy manifests stay
    # byte-stable modulo this one null key)
    if getattr(cfg, "sentinel", "off") != "off":
        recs = result.metrics if result is not None else []
        quars = [m for m in recs if m.get("event") == "quarantine"]
        doc["sentinel"] = {
            "mode": cfg.sentinel,
            "trips": sum(1 for m in recs
                         if m.get("event") == "sentinel_trip"),
            "rollbacks": sum(1 for m in recs
                             if m.get("event") == "rollback"),
            "quarantine_events": len(quars),
            "quarantined_nodes": sum(int(m.get("nodes", 0)) for m in quars),
        }
    else:
        doc["sentinel"] = None
    if result is not None:
        err = result.estimate_error
        doc["result"] = {
            "converged": bool(result.converged),
            "rounds": int(result.rounds),
            "wall_ms": float(result.wall_ms),
            "compile_ms": float(result.compile_ms),
            "num_nodes": int(result.num_nodes),
            "algorithm": result.algorithm,
            "estimate_error": None if err is None else float(err),
            "checkpoints": list(result.checkpoints),
            # "drain" when a graceful stop ended the run early (the serve
            # worker's SIGTERM path); None for normally-finished runs
            "stopped": getattr(result, "stopped", None),
        }
    if error is not None:
        doc["error"] = error
    return doc


def write_manifest(tel, cfg, topo, result=None, **kw) -> Optional[str]:
    """Write ``run.json`` into the telemetry dir (atomic tmp+rename).
    No-op (returns None) when telemetry is off."""
    if not tel.enabled or tel.dir is None:
        return None
    doc = build_manifest(tel, cfg, topo, result, **kw)
    path = os.path.join(tel.dir, "run.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)
    return path
