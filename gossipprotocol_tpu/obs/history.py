"""Cross-run regression tracking: a run index over bench records + manifests.

Every recorded bench session leaves a ``BENCH_rNN.json`` at the repo
root and (since the observatory) a persisted telemetry dir under
``artifacts/bench_telemetry_rNN/``; every ``--telemetry-dir`` run leaves
a ``run.json`` manifest. :func:`build_index` sweeps both into one
chronological ``artifacts/run_index.jsonl`` — a flat, append-friendly
record stream any later tool (or a human with ``jq``) can diff.

``python -m gossipprotocol_tpu history [ROOT]`` rebuilds the index and
prints the headline-metric trajectory: one line per bench round with the
value, the delta against the previous round, and the predicted-vs-actual
round ratio when the manifest recorded one. ``--metric SUBSTR`` filters
to matching metric names. Exit 0 on success, 2 when ROOT has no bench
records at all.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, TextIO

from gossipprotocol_tpu.utils.metrics import SCHEMA_VERSION

INDEX_RELPATH = os.path.join("artifacts", "run_index.jsonl")

_BENCH_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _load_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def _bench_records(root: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = _BENCH_RE.search(os.path.basename(path))
        doc = _load_json(path)
        if m is None or doc is None:
            continue
        parsed = doc.get("parsed") or {}
        rec: Dict[str, Any] = {
            "v": SCHEMA_VERSION,
            "kind": "bench",
            "seq": int(m.group(1)),
            "source": os.path.basename(path),
            "metric": parsed.get("metric"),
            "value": parsed.get("value"),
            "unit": parsed.get("unit"),
            "rounds": parsed.get("rounds"),
            "nodes": parsed.get("nodes"),
            "backend": parsed.get("backend"),
            "rc": doc.get("rc"),
        }
        if isinstance(parsed.get("phase_s"), dict):
            rec["phase_s"] = parsed["phase_s"]
        if parsed.get("prediction_ratio") is not None:
            rec["prediction_ratio"] = parsed["prediction_ratio"]
        # infra-outcome stamp (same metric names the /metrics exporter
        # uses, so bench infra-failures join daemon retry totals)
        for key in ("infra_failure", "probe_attempts", "infra_outcome",
                    "gossip_infra_retries_total",
                    "gossip_retry_backoff_seconds_total"):
            if parsed.get(key) is not None:
                rec[key] = parsed[key]
        out.append(rec)
    return out


def _manifest_records(root: str) -> List[Dict[str, Any]]:
    """Manifests under ``artifacts/`` (persisted bench telemetry and any
    run the user parked there), up to two levels deep."""
    out: List[Dict[str, Any]] = []
    pats = (os.path.join(root, "artifacts", "*", "run.json"),
            os.path.join(root, "artifacts", "*", "*", "run.json"))
    seen = set()
    for pat in pats:
        for path in sorted(glob.glob(pat)):
            # realpath: a symlinked artifacts dir must not index the
            # same manifest twice under two spellings
            real = os.path.realpath(path)
            if real in seen:
                continue
            seen.add(real)
            doc = _load_json(path)
            if doc is None or doc.get("kind") != "run_manifest":
                continue
            cfg = doc.get("config") or {}
            topo = doc.get("topology") or {}
            result = doc.get("result") or {}
            pred = doc.get("prediction") or {}
            rec = {
                "v": SCHEMA_VERSION,
                "kind": "run",
                "source": os.path.relpath(path, root),
                "algorithm": cfg.get("algorithm"),
                "topology": topo.get("kind"),
                "num_nodes": topo.get("num_nodes"),
                "backend": doc.get("backend"),
                "converged": result.get("converged"),
                "rounds": result.get("rounds"),
                "wall_ms": result.get("wall_ms"),
                "predicted_rounds": pred.get("predicted_rounds"),
                "actual_over_predicted": pred.get("actual_over_predicted"),
                "request_id": doc.get("request_id"),
            }
            rec.update(_resource_metrics(os.path.dirname(path)))
            out.append(rec)
            out.extend(_sweep_records(doc, rec["source"]))
    return out


def _sweep_records(doc: Dict[str, Any],
                   source: str) -> List[Dict[str, Any]]:
    """Sweep manifests fan out into the index: one ``sweep_lane`` record
    per lane (so lane outcomes diff like standalone runs) plus one
    ``sweep`` rollup row with the converged-lane fraction and the round
    percentiles. Non-sweep manifests contribute nothing here."""
    sweep = doc.get("sweep")
    if not isinstance(sweep, dict):
        return []
    cfg = doc.get("config") or {}
    topo = doc.get("topology") or {}
    base = {
        "v": SCHEMA_VERSION,
        "source": source,
        "algorithm": cfg.get("algorithm"),
        "topology": topo.get("kind"),
        "num_nodes": topo.get("num_nodes"),
        "backend": doc.get("backend"),
    }
    out: List[Dict[str, Any]] = []
    for lane in sweep.get("per_lane") or []:
        out.append({
            **base,
            "kind": "sweep_lane",
            "lane": lane.get("lane"),
            "seed": lane.get("seed"),
            "overrides": lane.get("overrides"),
            "converged": lane.get("converged"),
            "rounds": lane.get("rounds"),
        })
    out.append({
        **base,
        "kind": "sweep",
        "lanes": sweep.get("lanes"),
        "converged_fraction": sweep.get("converged_fraction"),
        "rounds_p50": sweep.get("rounds_p50"),
        "rounds_p95": sweep.get("rounds_p95"),
        "rounds_max": sweep.get("rounds_max"),
        "over_budget": sweep.get("over_budget"),
    })
    return out


def _resource_metrics(tel_dir: str) -> Dict[str, Any]:
    """Headline resource figures from a sibling ``resources.json``
    (resource observatory): peak host RSS and the chunk program's
    FLOPs / per-device argument bytes. Empty when the dir predates the
    observatory — old records index unchanged."""
    doc = _load_json(os.path.join(tel_dir, "resources.json"))
    if not doc or doc.get("kind") != "run_resources":
        return {}
    out: Dict[str, Any] = {}
    peak = (doc.get("host") or {}).get("peak_rss_bytes")
    if peak is not None:
        out["peak_rss_bytes"] = peak
    for prog in doc.get("programs") or []:
        if prog.get("label") != "chunk":
            continue
        flops = (prog.get("cost") or {}).get("flops")
        if flops is not None:
            out["chunk_flops"] = flops
        arg = (prog.get("memory") or {}).get("argument_size_in_bytes")
        if arg is not None:
            out["chunk_argument_bytes"] = arg
        break
    return out


def _journal_records(root: str) -> List[Dict[str, Any]]:
    """Daemon request records from serve/ queue-dir journals: one
    ``request`` row per request (id, admission verdict, queue wait,
    terminal phase, outcome) so daemon traffic diffs next to standalone
    runs. Journals are found at ROOT itself (ROOT *is* a queue dir),
    one level down, and under ``artifacts/``."""
    from gossipprotocol_tpu.serve import journal as journal_mod

    pats = (os.path.join(root, "journal.jsonl"),
            os.path.join(root, "*", "journal.jsonl"),
            os.path.join(root, "artifacts", "*", "journal.jsonl"))
    seen = set()
    out: List[Dict[str, Any]] = []
    for pat in pats:
        for path in sorted(glob.glob(pat)):
            real = os.path.realpath(path)
            if real in seen:
                continue
            seen.add(real)
            states = journal_mod.replay(journal_mod.read_journal(path))
            for st in states.values():
                last = st.last
                out.append({
                    "v": SCHEMA_VERSION,
                    "kind": "request",
                    "source": os.path.relpath(path, root),
                    "request_id": st.id,
                    "verdict": st.verdict,
                    "phase": st.phase,
                    "queue_wait_s": st.queue_wait_s,
                    "reason": last.get("reason"),
                    "converged": last.get("converged"),
                    "rounds": last.get("rounds"),
                    "batch": (st.first("batched") or {}).get("batch"),
                })
    return out


def _index_key(rec: Dict[str, Any], root: str) -> tuple:
    """Identity of an index record: kind + the *resolved* source path +
    the in-file id (request/lane). Two glob spellings of one artifact —
    symlinked dirs, a queue dir that is both ROOT and under artifacts/ —
    collapse to one key, so re-indexing never multiplies rows."""
    kind = rec.get("kind")
    src = os.path.realpath(os.path.join(root, rec.get("source") or ""))
    if kind == "bench":
        return (kind, rec.get("seq"), rec.get("metric"))
    if kind == "request":
        return (kind, src, rec.get("request_id"))
    if kind == "sweep_lane":
        return (kind, src, rec.get("lane"))
    return (kind, src, rec.get("request_id"))


def _dedupe(records: List[Dict[str, Any]],
            root: str) -> List[Dict[str, Any]]:
    seen = set()
    out: List[Dict[str, Any]] = []
    for rec in records:
        key = _index_key(rec, root)
        if key in seen:
            continue
        seen.add(key)
        out.append(rec)
    return out


def build_index(root: str, write: bool = True) -> List[Dict[str, Any]]:
    """Sweep ROOT for bench records, manifests, and daemon journals;
    optionally (re)write ``artifacts/run_index.jsonl`` (atomic
    tmp+rename — the index is a derived artifact, rebuilt whole each
    time). Records are deduped on (kind, resolved source, id) so
    overlapping sweep patterns and symlinked dirs index once."""
    records = _dedupe(_bench_records(root) + _manifest_records(root)
                      + _journal_records(root), root)
    if write and records:
        path = os.path.join(root, INDEX_RELPATH)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            for rec in records:
                fh.write(json.dumps(rec) + "\n")
        os.replace(tmp, path)
    return records


def _fmt_delta(cur: Any, prev: Any) -> str:
    if not isinstance(cur, (int, float)) or not isinstance(prev, (int, float)):
        return ""
    if prev <= 0:
        return ""
    d = (cur - prev) / prev
    return f"  {d:+.1%}"


def render_history(records: List[Dict[str, Any]], out: TextIO,
                   metric_filter: Optional[str] = None) -> None:
    benches = [r for r in records if r["kind"] == "bench"
               and r.get("metric")
               and (metric_filter is None or metric_filter in r["metric"])]
    by_metric: Dict[str, List[Dict[str, Any]]] = {}
    for r in benches:
        by_metric.setdefault(r["metric"], []).append(r)
    for metric, rows in by_metric.items():
        rows.sort(key=lambda r: r["seq"])
        out.write(f"{metric}:\n")
        prev = None
        for r in rows:
            val = r.get("value")
            line = (f"  r{r['seq']:02d}  "
                    + (f"{val:10.3f} {r.get('unit') or ''}"
                       if isinstance(val, (int, float)) else f"{val!r:>10}"))
            if r.get("rounds") is not None:
                line += f"  {r['rounds']} rounds"
            if r.get("backend"):
                line += f"  [{r['backend']}]"
            line += _fmt_delta(val, (prev or {}).get("value"))
            if r.get("prediction_ratio") is not None:
                line += f"  pred-ratio {r['prediction_ratio']:.2f}"
            if r.get("gossip_infra_retries_total"):
                line += (f"  infra-retries "
                         f"{r['gossip_infra_retries_total']}")
            if r.get("infra_failure") or (
                    r.get("infra_outcome") == "infra_failure"):
                line += "  INFRA-FAILURE"
            out.write(line + "\n")
            prev = r
        out.write("\n")
    runs = [r for r in records if r["kind"] == "run"]
    if runs:
        out.write(f"indexed manifests ({len(runs)}):\n")
        for r in runs:
            line = (f"  {r.get('algorithm', '?')} on "
                    f"{r.get('topology', '?')}-{r.get('num_nodes', '?')}: ")
            if r.get("rounds") is not None:
                line += f"{r['rounds']} rounds"
            if isinstance(r.get("wall_ms"), (int, float)):
                line += f", {r['wall_ms']:.1f} ms"
            if r.get("actual_over_predicted") is not None:
                line += f", {r['actual_over_predicted']:.2f}x predicted"
            if isinstance(r.get("peak_rss_bytes"), (int, float)):
                line += f", peak RSS {r['peak_rss_bytes'] / 2**20:.0f} MiB"
            line += f"  ({r['source']})"
            out.write(line + "\n")
    sweeps = [r for r in records if r["kind"] == "sweep"]
    if sweeps:
        out.write(f"\nindexed sweeps ({len(sweeps)}):\n")
        for r in sweeps:
            line = (f"  {r.get('algorithm', '?')} on "
                    f"{r.get('topology', '?')}-{r.get('num_nodes', '?')}: "
                    f"{r.get('lanes', '?')} lanes")
            if isinstance(r.get("converged_fraction"), (int, float)):
                line += f", {r['converged_fraction']:.0%} converged"
            if r.get("rounds_p50") is not None:
                line += (f", rounds p50 {r['rounds_p50']:.0f}"
                         f" / p95 {r['rounds_p95']:.0f}")
            if r.get("over_budget"):
                line += ", OVER BUDGET"
            line += f"  ({r['source']})"
            out.write(line + "\n")
    requests = [r for r in records if r["kind"] == "request"]
    if requests:
        out.write(f"\nindexed daemon requests ({len(requests)}):\n")
        for r in requests:
            line = f"  {r.get('request_id')}  {r.get('phase')}"
            if r.get("verdict") == "refused":
                line += f"  ({r.get('reason')})"
            elif r.get("phase") == "finished":
                line += (f"  converged={r.get('converged')}"
                         f" rounds={r.get('rounds')}")
            if r.get("queue_wait_s") is not None:
                line += f"  queue_wait={r['queue_wait_s']:.2f}s"
            if r.get("batch"):
                line += f"  batch={r['batch']}"
            line += f"  ({r['source']})"
            out.write(line + "\n")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print("usage: python -m gossipprotocol_tpu history [ROOT] "
              "[--metric SUBSTR] [--no-write]")
        return 0
    root = "."
    metric: Optional[str] = None
    write = True
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--metric":
            if i + 1 >= len(argv):
                print("history: --metric needs a value", file=sys.stderr)
                return 2
            metric = argv[i + 1]
            i += 2
        elif a == "--no-write":
            write = False
            i += 1
        else:
            root = a
            i += 1
    if not os.path.isdir(root):
        print(f"history: {root!r} is not a directory", file=sys.stderr)
        return 2
    records = build_index(root, write=write)
    if not records:
        print(f"history: no BENCH_r*.json or manifests under {root!r}",
              file=sys.stderr)
        return 2
    render_history(records, sys.stdout, metric_filter=metric)
    if write:
        print(f"index: {os.path.join(root, INDEX_RELPATH)} "
              f"({len(records)} records)")
    return 0
