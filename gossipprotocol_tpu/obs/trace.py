"""On-device per-round convergence traces.

The telemetry counters (PR 5) see chunk-granularity aggregates; the
observatory needs the *per-round* curve — how far from consensus is the
system right now, at what rate is it closing, is mass conserved — without
ever leaving the jitted chunk loop. :func:`make_trace_fn` mirrors
:func:`~gossipprotocol_tpu.obs.counters.make_counter_fn`'s dispatch: one
trace-row function per protocol family, each implemented next to the
round it observes (``protocols/pushsum.py``, ``protocols/gossip.py``,
``protocols/diffusion.py``, ``learn/sgp.py``).

The returned function has one fixed call shape for both engines::

    trace_fn(new_state) -> float32[NUM_TRACE_COLS]

and is called once per round *inside* the jitted ``while_loop`` body; the
row lands in a ``[chunk_rounds, NUM_TRACE_COLS]`` side buffer next to the
counter buffer. Under ``shard_map`` the row functions take psum/pmax
reduction closures, so every component is already replicated and the
buffer's out-spec stays ``P()`` — exactly the counters' contract.

Correctness contract (pinned by tests/test_observatory.py):

* trace functions only **read** the post-round state — no state bit and
  no PRNG stream is perturbed, so the trajectory with traces on is
  bitwise identical to traces off;
* with ``trace_fn=None`` the chunk runners build the literal pre-trace
  programs (program-text goldens, single-chip and 2-shard).

Columns (NaN = not applicable to the protocol):

* ``residual`` — push-sum: max over alive nodes (and payload dims) of
  |s/w − mean|, the consensus residual against the alive-mass mean;
  gossip: fraction of alive nodes the rumor has not reached yet (both
  decrease toward 0 on a healthy run).
* ``converged_frac`` — converged alive nodes / alive nodes.
* ``mass_s`` / ``mass_w`` — Σs (summed over payload dims) and Σw over
  every row, the conservation terms. f32 trace precision; the ULP-exact
  drift tracking stays with the counter machinery.
* ``train_loss`` — SGP: mean train loss over alive nodes.

Host side, :class:`TraceWriter` appends rows to a crash-durable
``trace.jsonl``, downsampling past a configurable cap: whenever another
``cap`` rows have been written the round stride doubles, so a run of R
rounds writes at most ``cap · (1 + log2(R / cap))`` lines — a 100k-round
run at the default cap of 4096 stays under ~25k lines.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from gossipprotocol_tpu.utils.metrics import SCHEMA_VERSION

TRACE_FIELDS = ("residual", "converged_frac", "mass_s", "mass_w",
                "train_loss")
NUM_TRACE_COLS = len(TRACE_FIELDS)

TRACE_CAP_DEFAULT = 4096


def default_trace_cap() -> int:
    return int(os.environ.get("GOSSIP_TPU_TRACE_CAP", TRACE_CAP_DEFAULT))


def make_trace_fn(
    topo,
    cfg,
    *,
    all_sum: Optional[Callable] = None,
    all_max: Optional[Callable] = None,
) -> Callable:
    """Build the per-round trace-row function for this run's branch.

    ``all_sum`` reduces over the node axis preserving trailing dims
    (``jnp.sum(x, axis=0)`` single-chip, a psum closure under
    ``shard_map``); ``all_max`` is the full max (a pmax closure under
    ``shard_map``). ``topo`` is unused today but kept for signature
    parity with :func:`~gossipprotocol_tpu.obs.counters.make_counter_fn`.
    """
    del topo
    kw: Dict[str, Any] = {}
    if all_sum is not None:
        kw["all_sum"] = all_sum
    if all_max is not None:
        kw["all_max"] = all_max
    if cfg.algorithm == "gossip":
        from gossipprotocol_tpu.protocols.gossip import gossip_trace_row

        return lambda s: gossip_trace_row(s, **kw)
    if cfg.workload in ("sgp", "gala"):
        from gossipprotocol_tpu.learn.sgp import sgp_trace_row

        return lambda s: sgp_trace_row(s, **kw)
    if cfg.fanout == "all":
        from gossipprotocol_tpu.protocols.diffusion import (
            diffusion_trace_row,
        )

        return lambda s: diffusion_trace_row(s, **kw)
    from gossipprotocol_tpu.protocols.pushsum import pushsum_trace_row

    return lambda s: pushsum_trace_row(s, **kw)


class TraceWriter:
    """Append-only ``trace.jsonl`` with stride-doubling downsampling.

    Rows arrive in per-chunk batches (one float32 row per executed
    round); only rounds divisible by the current stride are written.
    Every ``cap`` written rows the stride doubles, bounding the file at
    ``cap·(1 + log2(total_rounds/cap))`` lines. Line-buffered append, so
    a killed run keeps everything written so far.
    """

    def __init__(self, path: str, cap: Optional[int] = None):
        self.path = path
        self.cap = max(2, int(cap if cap is not None else default_trace_cap()))
        self.stride = 1
        self.rows_written = 0
        self.last_round = 0
        self._fh = open(path, "a", buffering=1)

    def add(self, start_round: int, rows: np.ndarray) -> None:
        """Append the rows for rounds ``start_round+1 .. start_round+m``
        (``rows`` is ``[m, NUM_TRACE_COLS]``, the valid prefix of one
        chunk's trace buffer)."""
        if self._fh.closed:
            return
        rows = np.asarray(rows, np.float64)
        for i in range(rows.shape[0]):
            rnd = start_round + 1 + i
            self.last_round = rnd
            if rnd % self.stride:
                continue
            rec: Dict[str, Any] = {
                "v": SCHEMA_VERSION, "kind": "trace", "round": rnd,
            }
            for name, val in zip(TRACE_FIELDS, rows[i]):
                v = float(val)
                if v == v:  # NaN column = not applicable to this protocol
                    rec[name] = v
            self._fh.write(json.dumps(rec) + "\n")
            self.rows_written += 1
            if self.rows_written % self.cap == 0:
                self.stride *= 2

    def summary(self) -> Dict[str, int]:
        return {
            "rows_written": self.rows_written,
            "stride": self.stride,
            "cap": self.cap,
            "last_round": self.last_round,
        }

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Read a ``trace.jsonl`` (the file, not the dir); missing file or
    torn lines are tolerated — traces are a best-effort record."""
    rows: List[Dict[str, Any]] = []
    if not os.path.isfile(path):
        return rows
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn final line from a killed run
            if rec.get("kind") == "trace":
                rows.append(rec)
    return rows
