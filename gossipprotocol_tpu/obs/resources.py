"""Resource observatory: what a run *costs*, not just how long it takes.

Three record families, all host-side (nothing here touches a compiled
program, so the zero-cost-off contract is trivially safe):

* **compiled-program introspection** — the driver hands every freshly
  compiled chunk program to :meth:`ResourceRecorder.record_compiled`,
  which asks XLA for ``cost_analysis()`` (FLOPs, bytes accessed) and
  ``memory_analysis()`` (argument / output / temp / generated-code
  bytes — the per-device HBM footprint the capacity planner predicts);
* **samples** — host RSS (``/proc/self/status``) plus per-device
  ``memory_stats()`` ``bytes_in_use``, taken at span boundaries and at
  close, capped at :data:`MAX_SAMPLES` (a dropped-sample counter keeps
  truncation loud);
* **notes** — scalar facts other layers compute anyway (edge-share
  ``all_to_all`` bytes per round, routed table bytes) parked where the
  report and capacity validation can find them.

Everything is wrapped in broad ``except Exception`` guards: resource
introspection must never be the reason a run dies.  The document lands
as ``resources.json`` beside the manifest (atomic tmp+rename) when the
telemetry hub closes.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from gossipprotocol_tpu.utils.metrics import SCHEMA_VERSION

# span-boundary samples kept before further ones are dropped (counted)
MAX_SAMPLES = 256

# cost_analysis() keys worth keeping verbatim (the per-op breakdown keys
# like "bytes accessed0{}" are backend noise; these are the headline)
_COST_KEYS = ("flops", "transcendentals", "bytes accessed",
              "optimal_seconds", "utilization")


def host_rss_bytes() -> Optional[int]:
    """Current resident set size, or None when unknowable."""
    return _proc_status_bytes("VmRSS")


def host_peak_rss_bytes() -> Optional[int]:
    """Peak (high-water-mark) resident set size."""
    peak = _proc_status_bytes("VmHWM")
    if peak is not None:
        return peak
    try:  # non-Linux fallback: ru_maxrss is KiB on Linux, bytes on macOS
        import resource
        import sys

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(rss if sys.platform == "darwin" else rss * 1024)
    except Exception:
        return None


def _proc_status_bytes(field: str) -> Optional[int]:
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith(field + ":"):
                    return int(line.split()[1]) * 1024  # value is in kB
    except Exception:
        pass
    return None


def device_info_doc() -> List[Dict[str, Any]]:
    """One record per jax device: identity + ``memory_stats()`` when the
    backend exposes them (CPU returns None — recorded as absent, which is
    itself the answer \"no HBM accounting on this backend\")."""
    out: List[Dict[str, Any]] = []
    try:
        import jax

        for dev in jax.devices():
            rec: Dict[str, Any] = {
                "id": int(dev.id),
                "platform": str(dev.platform),
                "device_kind": str(getattr(dev, "device_kind", "?")),
            }
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if stats:
                rec["memory_stats"] = {
                    k: int(v) for k, v in stats.items()
                    if isinstance(v, (int, float))
                }
            out.append(rec)
    except Exception:
        pass
    return out


def _cost_doc(compiled) -> Optional[Dict[str, Any]]:
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):  # jax < 0.5 wraps in a list
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None
    return {k: float(v) for k, v in cost.items()
            if k in _COST_KEYS and isinstance(v, (int, float))}


def _memory_doc(compiled) -> Optional[Dict[str, Any]]:
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None
    doc: Dict[str, Any] = {}
    for name in dir(mem):
        if name.startswith("_") or "proto" in name:
            continue
        v = getattr(mem, name, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            doc[name] = int(v)
    return doc or None


class ResourceRecorder:
    """Accumulates the resource document for one run."""

    def __init__(self) -> None:
        self._t0 = time.perf_counter()
        self.programs: List[Dict[str, Any]] = []
        self.samples: List[Dict[str, Any]] = []
        self.samples_dropped = 0
        self.notes: Dict[str, Any] = {}

    def record_compiled(self, label: str, compiled, **attrs: Any) -> None:
        """Introspect one compiled chunk program; never raises."""
        try:
            rec: Dict[str, Any] = {"label": label}
            rec.update({k: v for k, v in attrs.items() if v is not None})
            cost = _cost_doc(compiled)
            if cost:
                rec["cost"] = cost
            mem = _memory_doc(compiled)
            if mem:
                rec["memory"] = mem
            self.programs.append(rec)
        except Exception:
            pass

    def sample(self, tag: str) -> None:
        """Snapshot host RSS + total device bytes-in-use; capped."""
        if len(self.samples) >= MAX_SAMPLES:
            self.samples_dropped += 1
            return
        rec: Dict[str, Any] = {
            "tag": tag,
            "t_s": round(time.perf_counter() - self._t0, 6),
        }
        rss = host_rss_bytes()
        if rss is not None:
            rec["rss_bytes"] = rss
        try:
            import jax

            in_use = 0
            have = False
            for dev in jax.devices():
                stats = dev.memory_stats()
                if stats and "bytes_in_use" in stats:
                    in_use += int(stats["bytes_in_use"])
                    have = True
            if have:
                rec["device_bytes_in_use"] = in_use
        except Exception:
            pass
        self.samples.append(rec)

    def note(self, key: str, value: Any) -> None:
        self.notes[key] = value

    def doc(self) -> Dict[str, Any]:
        return {
            "v": SCHEMA_VERSION,
            "kind": "run_resources",
            "host": {
                "rss_bytes": host_rss_bytes(),
                "peak_rss_bytes": host_peak_rss_bytes(),
            },
            "devices": device_info_doc(),
            "programs": self.programs,
            "samples": self.samples,
            "samples_dropped": self.samples_dropped,
            "notes": self.notes,
        }


def write_resources(out_dir: str, recorder: ResourceRecorder) -> Optional[str]:
    """Write ``resources.json`` (atomic tmp+rename); never raises."""
    try:
        path = os.path.join(out_dir, "resources.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(recorder.doc(), fh, indent=2)
            fh.write("\n")
        os.replace(tmp, path)
        return path
    except Exception:
        return None


def load_resources(out_dir: str) -> Optional[Dict[str, Any]]:
    """Read ``resources.json`` from a telemetry dir; None when absent or
    unreadable (partial dirs are normal, not errors)."""
    try:
        with open(os.path.join(out_dir, "resources.json")) as fh:
            return json.load(fh)
    except Exception:
        return None
