"""Analytic HBM capacity planner: will (n, topology, shards) fit at all?

The memory-side twin of :mod:`gossipprotocol_tpu.obs.predict`: where that
module predicts *rounds* from spectral geometry, this one predicts
*per-device bytes* from plan geometry — state rows, delivery tables,
edge-share temporaries, telemetry buffers — **before any plan build**,
so an over-capacity 100M/1B request is refused in milliseconds instead
of dying mid-build with an opaque allocator error (cf. the MULTICHIP r5
rc=124 tail).

Model structure:

* **state** — measured, not modeled: the protocol state pytree is built
  once at a tiny probe size with the *same* config knobs (algorithm,
  payload dim, dtype, workload) and its exact bytes/row scale linearly
  to any n. Immune to layout drift in ``protocols/state.py``.
* **delivery** — analytic per-path formulas mirroring
  ``engine.driver.device_arrays`` / the sharded dispatch: dense table
  vs CSR for fanout-one sampling, edge lists for diffusion,
  ~:data:`ROUTED_BYTES_PER_EDGE` B/directed edge for routed plans
  (the ``ops/sharddelivery.py`` figure), all divided by the shard count
  where the real arrays shard.
* **edges** — closed-form per topology family (``line`` 2(n−1), grids
  ~6n/7n, ER ``avg_degree·n``, …) so planning 1B nodes never builds a
  1B-node graph; exact counts are passed in when a topology exists.

Validation: the predicted argument bytes track XLA ``memory_analysis()``
within a pinned tolerance on small configs (``tests/test_resources.py``).

Device capacity comes from ``$GOSSIP_TPU_HBM_BYTES`` (override / CI) or
``device.memory_stats()['bytes_limit']``; CPU backends expose neither,
so the preflight is a no-op there unless the env var is set.

``python -m gossipprotocol_tpu plan N TOPOLOGY [ALGO] [flags]`` renders
the breakdown, predicts the max feasible n at the same geometry, and
exits 1 for an over-capacity request — the admission-control hook.
"""

from __future__ import annotations

import math
import os
import sys
from typing import Any, Dict, Optional, Tuple

__all__ = ["CapacityError", "edges_estimate", "estimate_run_bytes",
           "estimate_for_topology", "device_capacity_bytes",
           "max_feasible_nodes", "estimate_build_host_bytes",
           "suggest_build_shards", "build_rss_budget_bytes",
           "preflight", "main"]


class CapacityError(ValueError):
    """A requested run cannot fit in device memory."""


# measured routed-plan footprint (ops/sharddelivery.py docstring):
# ~86 bytes per directed edge across plan_in/m/out + class tables
ROUTED_BYTES_PER_EDGE = 86
# pallas gather-table slot cost (ops/pallasdelivery.py): one int32 per
# f32 reduce slot resident, plus the per-tile source-row table (worst
# case one entry per slot) once the source overflows VMEM residency
PALLAS_SLOT_BYTES_RESIDENT = 4
PALLAS_SLOT_BYTES_BUCKET = 8
# refuse runs predicted above this fraction of per-device capacity —
# XLA needs allocator headroom beyond the model's accounted buffers
DEFAULT_SAFETY = 0.9
# probe size for the measured state bytes/row (any small multiple of
# every supported shard count; the probe build costs ~ms)
_PROBE_ROWS = 512

_state_probe_cache: Dict[Tuple, Tuple[float, int]] = {}


def _dtype_bytes(cfg) -> int:
    import jax.numpy as jnp

    return int(jnp.dtype(cfg.dtype).itemsize)


def edges_estimate(kind: str, num_nodes: int, *, avg_degree: float = 8.0,
                   m: int = 4, k: int = 6) -> Tuple[int, int]:
    """(directed edge count, max-degree estimate) for a topology family,
    closed-form — no graph build. The implicit complete graph has no
    materialized edges at all (its delivery is arithmetic)."""
    from gossipprotocol_tpu.topology.registry import canonical_name

    n = int(num_nodes)
    kind = canonical_name(kind)
    if kind == "line":
        return max(0, 2 * (n - 1)), 2
    if kind == "full":
        return 0, 0  # implicit: no edge arrays, no sampling table
    if kind == "3D":
        return 6 * n, 6
    if kind == "imp3D":
        return 7 * n, 8  # 3D lattice + one imperfect extra per node
    if kind == "erdos_renyi":
        # max degree: Poisson tail bound, generous enough for dense/CSR
        # dispatch at the default avg_degree=8
        return int(avg_degree * n), int(avg_degree + 6 * math.sqrt(avg_degree) + 4)
    if kind == "power_law":
        return 2 * m * n, int(math.sqrt(max(n, 1)) + 2 * m)  # hub-bound
    if kind == "small_world":
        return k * n, k + 8
    raise CapacityError(f"no edge model for topology {kind!r}")


def _state_row_bytes(cfg) -> Tuple[float, int]:
    """(bytes per state row, fixed bytes) measured from a probe build of
    the actual protocol state pytree with this config's knobs."""
    import dataclasses

    key = (cfg.algorithm, cfg.workload, int(cfg.payload_dim),
           str(cfg.dtype), cfg.fanout, cfg.predicate)
    hit = _state_probe_cache.get(key)
    if hit is not None:
        return hit
    from gossipprotocol_tpu.engine.driver import build_protocol
    from gossipprotocol_tpu.topology import build_topology

    import jax

    probe_cfg = dataclasses.replace(cfg, telemetry=None, seed=0, sweep=None)
    topo = build_topology("line", _PROBE_ROWS)
    state, *_ = build_protocol(topo, probe_cfg, num_rows=_PROBE_ROWS)
    row = 0.0
    fixed = 0
    for leaf in jax.tree_util.tree_leaves(state):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == _PROBE_ROWS:
            row += leaf.nbytes / _PROBE_ROWS
        else:
            fixed += int(getattr(leaf, "nbytes", 0))
    _state_probe_cache[key] = (row, fixed)
    return row, fixed


def _pallas_gather_bytes(e_local: int, local_rows: int,
                         max_degree: int) -> int:
    """Single-chip pallas delivery tables, sized the way
    ``ops.pallasdelivery.build_gather_plan`` sizes them: the pre-reduce
    map covers the class-layout pair slots (edges PLUS the BLK-row
    quantization floor every populated small class pays), the output map
    covers 2·n slots, each map priced per slot by the gather mode its
    source size forces (resident int32 index vs bucketed index + row
    table), plus the int32 degree vector."""
    from gossipprotocol_tpu.ops.classops import BLK
    from gossipprotocol_tpu.ops.pallasdelivery import (
        LANES, TILE, _resident_rows,
    )

    pairs = _class_pair_slots(e_local, max_degree)

    resident = _resident_rows()

    def per_slot(src_rows: int) -> int:
        return (PALLAS_SLOT_BYTES_RESIDENT if src_rows <= resident
                else PALLAS_SLOT_BYTES_BUCKET)

    pre_slots = -(-2 * pairs // TILE) * TILE
    out_slots = -(-2 * local_rows // TILE) * TILE
    pre = per_slot(-(-(2 * local_rows + 1) // LANES)) * pre_slots
    out = per_slot(-(-2 * pairs // LANES)) * out_slots
    return pre + out + 4 * local_rows


def _wire_bytes(cfg) -> int:
    """Bytes per exchange-slab slot under ``payload_wire`` (the sharded
    routed-push wire format): f32 ships raw, bf16 halves, int8 quarters
    (the per-row f32 scale sidecar is O(num_shards), noise here)."""
    return {"f32": 4, "bf16": 2, "int8": 1}[
        getattr(cfg, "payload_wire", "f32")]


def _class_pair_slots(num_edges: int, max_degree: int) -> int:
    """Class-layout pair-slot upper bound: edges plus a per-class
    quantization floor (mirrors ``delivery.degree_classes`` /
    ``class_layout``). Small classes (c <= 64) pay the flat layout's
    BLK-row floor as before; split hub classes (one ceil-pow2 class per
    octave from 512 up to max_degree) pay the hub-splitting layout's
    floor instead — each sub-class region is node-capacity padded to at
    least 8 rows, so a split class costs at least ``8 * c`` pairs even
    with a single member, and never less than the old BLK-row floor."""
    from gossipprotocol_tpu.ops.classops import BLK
    from gossipprotocol_tpu.ops.pallasdelivery import LANES

    cp2 = 1 << max(0, (max(1, max_degree) - 1)).bit_length()
    if cp2 > 64:
        cp2 = max(cp2, 512)  # 128/256 band merges into 512
    n_small = min(cp2.bit_length(), 7)  # classes 1..64
    floors = n_small * BLK * (LANES // 2)
    c = 512
    while c <= cp2:
        floors += max(BLK * (LANES // 2), 8 * c)
        c *= 2
    return num_edges + floors


def _hub_split_summary(max_degree: int) -> Optional[Dict[str, int]]:
    """Predicted hub-splitting layout geometry from the degree range:
    one split class per octave from 512 up to the merged ceil-pow2 of
    ``max_degree`` (an upper bound — only populated octaves split on a
    real graph), each contributing ``2c / 128`` sub-classes. None when
    the layout has no split classes (degree-regular regime: the literal
    pre-split kernels trace)."""
    cp2 = 1 << max(0, (max(1, max_degree) - 1)).bit_length()
    if cp2 > 64:
        cp2 = max(cp2, 512)
    if cp2 < 512:
        return None
    split = [512 << i for i in range((cp2 // 512).bit_length())]
    return {
        "classes": len(split),
        "subclasses": sum((2 * c) // 128 for c in split),
        "max_degree": int(max_degree),
    }


def megakernel_vmem_estimate(num_nodes: int, num_edges: int,
                             max_degree: int) -> int:
    """Closed-form VMEM footprint of the round-loop megakernel, the
    analytic twin of ``ops.megakernel.megakernel_vmem_bytes`` (which
    prices a *built* plan): state I/O cubes (5 carries in + out plus the
    degree row, all padded to (rp, 128) f32/i32), both gather index maps
    and their source cubes, the gathered pre/out vectors, and the
    double-buffered per-class reduce region (bounded by the whole
    gathered pre cube — the K-round loop reuses these same buffers, so
    the footprint is independent of K)."""
    from gossipprotocol_tpu.ops.pallasdelivery import (
        LANES, TILE, TILE_ROWS,
    )

    n = int(num_nodes)
    rp = -(-n // TILE) * TILE_ROWS
    pairs = _class_pair_slots(num_edges, max_degree)
    pre_slots = -(-2 * pairs // TILE) * TILE
    out_slots = -(-2 * n // TILE) * TILE
    pre_src = -(-(2 * n + 1) // LANES)
    out_src = -(-2 * pairs // LANES)
    state_io = 11 * rp * LANES * 4
    idx = (pre_slots + out_slots) * 4
    srcs = (pre_src + out_src) * LANES * 4
    gathered = (pre_slots + out_slots) * 4
    region = pre_slots * 8  # 2x-buffered largest-class region bound
    return state_io + idx + srcs + gathered + region


def _delivery_bytes(cfg, n_pad: int, local_rows: int, num_shards: int,
                    num_edges: int, max_degree: int,
                    implicit_full: bool) -> Tuple[int, str]:
    """Per-device delivery-table bytes + which path was modeled.

    Mirrors ``engine.driver.device_arrays`` and the sharded dispatch in
    ``parallel/sharded.py`` — when those grow a new path, grow this.
    """
    from gossipprotocol_tpu.protocols.sampling import DENSE_MAX_DEGREE

    if implicit_full:
        return 0, "implicit-full"
    is_pushsum = cfg.algorithm != "gossip"
    e_local = -(-num_edges // num_shards)  # ceil: padded per-shard blocks
    if is_pushsum and cfg.fanout == "all":
        if cfg.delivery == "routed":
            # routed plans: ~86 B/edge of tables per device (push design
            # owns E/S edges; single-chip owns them all) + the exchange
            # slab [num_shards, 2·block_pairs], priced at the wire
            # format's bytes/slot (payload_wire=bf16/int8 compresses it)
            slab = (_wire_bytes(cfg) * num_edges if num_shards > 1
                    else 0)
            return ROUTED_BYTES_PER_EDGE * e_local + slab, "routed"
        if cfg.delivery == "pallas":
            if num_shards > 1:
                # sharded pallas keeps the push design's per-shard plan
                # tables (same geometry) — only the exchange transport
                # changes, and the remote-copy landing buffer matches
                # the all_to_all slab byte-for-byte (and compresses
                # identically under payload_wire)
                slab = _wire_bytes(cfg) * num_edges
                return ROUTED_BYTES_PER_EDGE * e_local + slab, "pallas"
            return _pallas_gather_bytes(e_local, local_rows,
                                        max_degree), "pallas"
        if cfg.delivery == "megakernel":
            # single-chip only (validated upstream): same HBM-side gather
            # tables as the resident pallas path — the K-round fusion
            # changes VMEM pressure (see megakernel_vmem_estimate), not
            # the argument footprint
            return _pallas_gather_bytes(e_local, local_rows,
                                        max_degree), "megakernel"
        # diffusion edge list: src+dst int32 per edge (+ valid byte when
        # sharded blocks carry padding) + row-aligned degree
        per_edge = 8 + (1 if num_shards > 1 else 0)
        return per_edge * e_local + 4 * local_rows, "diffusion-edges"
    # fanout-one sampling (and gossip): dense row table when the max
    # degree is bounded, else the replicated CSR pool
    if max_degree <= DENSE_MAX_DEGREE and os.environ.get(
            "GOSSIP_TPU_DENSE", "1") != "0":
        return 4 * local_rows * (max_degree + 1), "dense-table"
    # CSRNeighbors replicates on every device: starts/degree [n] +
    # indices [E], all int32
    return 4 * (2 * n_pad + num_edges), "csr-replicated"


def estimate_run_bytes(
    kind: str,
    num_nodes: int,
    cfg,
    num_devices: int = 1,
    *,
    num_edges: Optional[int] = None,
    max_degree: Optional[int] = None,
    implicit_full: Optional[bool] = None,
    telemetry_on: bool = True,
    avg_degree: float = 8.0,
    m: int = 4,
    k: int = 6,
) -> Dict[str, Any]:
    """Predicted per-device footprint for a (topology, n, config, shards)
    request. Pass exact ``num_edges``/``max_degree`` when a topology
    exists; otherwise the family's closed-form estimate is used."""
    from gossipprotocol_tpu.parallel.mesh import padded_size
    from gossipprotocol_tpu.topology.registry import canonical_name

    n = int(num_nodes)
    if n < 1:
        raise CapacityError(f"num_nodes must be >= 1, got {n}")
    num_shards = max(1, int(num_devices))
    if implicit_full is None:
        implicit_full = canonical_name(kind) == "full"
    if num_edges is None or max_degree is None:
        e_est, d_est = edges_estimate(
            kind, n, avg_degree=avg_degree, m=m, k=k)
        num_edges = e_est if num_edges is None else int(num_edges)
        max_degree = d_est if max_degree is None else int(max_degree)
    n_pad = padded_size(n, num_shards) if num_shards > 1 else n
    local_rows = n_pad // num_shards
    B = _dtype_bytes(cfg)
    d = int(cfg.payload_dim)

    # sweep lanes stack per-run state [B, ...] under vmap: everything
    # per-trajectory (state, workload data, round temporaries, counter
    # buffers) is paid once per lane; delivery tables stay shared — the
    # topology is a structural invariant across the sweep
    sweep = getattr(cfg, "sweep", None)
    lanes = max(1, int(getattr(sweep, "lanes", 1))) if sweep is not None else 1

    row_bytes, fixed_bytes = _state_row_bytes(cfg)
    state_bytes = (int(row_bytes * local_rows) + fixed_bytes) * lanes

    delivery_bytes, path = _delivery_bytes(
        cfg, n_pad, local_rows, num_shards, num_edges, max_degree,
        implicit_full)

    # SGP data shards row-wise with the state: A [rows, samples, d] +
    # b [rows, samples]
    data_bytes = 0
    if cfg.workload in ("sgp", "gala"):
        data_bytes = local_rows * int(cfg.sgp_samples) * (d + 1) * B * lanes

    # transient estimate: the delivery scratch XLA materializes inside a
    # round (segment_sum accumulators / edge-share vectors), the piece
    # memory_analysis reports as temp. Doubled for double buffering.
    e_local = -(-num_edges // num_shards)
    if implicit_full:
        temp_bytes = 2 * local_rows * (d + 1) * B
    elif cfg.algorithm != "gossip" and cfg.fanout == "all":
        per_round_edges = -(-e_local // max(1, int(cfg.edge_chunks)))
        temp_bytes = 2 * per_round_edges * (d + 1) * B + \
            2 * n_pad * (d + 1) * B // num_shards
    else:
        temp_bytes = 2 * n_pad * (d + 1) * B // num_shards
    temp_bytes *= lanes

    telemetry_bytes = 0
    if telemetry_on:
        slots = cfg.resolve_chunk_rounds(
            n, None if implicit_full else num_edges)
        # counters [slots,3] i32 + shard partials + trace [slots,5] f32
        telemetry_bytes = slots * (12 + 12 + 20) * lanes

    argument_bytes = state_bytes + delivery_bytes + data_bytes + 16
    total = argument_bytes + temp_bytes + telemetry_bytes
    extra_per_device: Dict[str, int] = {}
    if path == "pallas" and num_shards == 1:
        # mirror the gather kernel's VMEM story (ops/pallasdelivery.py):
        # a source at or under the resident-row threshold rides whole in
        # VMEM; past it the kernel stages [R, 128] row slabs, R bounded
        # by the 1024 slots of one destination tile. Advisory (VMEM is
        # not HBM) — rendered by `plan` so kernel-budget regressions
        # show up before a Mosaic allocation failure does
        from gossipprotocol_tpu.ops.pallasdelivery import (
            LANES as _PL_LANES, TILE as _PL_TILE, _resident_rows,
        )

        src_rows = -(-(2 * n + 1) // _PL_LANES)
        # bucket-mode R is capped by the slots of one destination tile
        scratch_rows = (src_rows if src_rows <= _resident_rows()
                        else min(src_rows, _PL_TILE))
        extra_per_device["pallas_vmem_scratch_bytes"] = (
            scratch_rows * _PL_LANES * 4)
    if path == "megakernel":
        # advisory like pallas_vmem_scratch_bytes: the whole-round fused
        # kernel holds state + both gather cubes resident — a number
        # past ~16 MiB predicts a Mosaic allocation failure before one
        # happens (K does not enter: the round loop reuses the buffers)
        extra_per_device["megakernel_vmem_bytes"] = (
            megakernel_vmem_estimate(n, num_edges, max_degree))
    if (num_shards > 1 and path in ("routed", "pallas")
            and getattr(cfg, "payload_wire", "f32") != "f32"):
        # per-device wire bytes each round under the compressed format,
        # next to the f32 figure it replaces (manifest's
        # exchange_bytes_per_round reports the same quantity measured)
        extra_per_device["wire_exchange_bytes_per_round"] = (
            _wire_bytes(cfg) * num_edges)
        extra_per_device["f32_exchange_bytes_per_round"] = 4 * num_edges
    return {
        "kind": canonical_name(kind),
        "num_nodes": n,
        "num_padded": n_pad,
        "num_devices": num_shards,
        "lanes": lanes,
        "num_edges": int(num_edges),
        "delivery_path": path,
        "hub_split": (_hub_split_summary(max_degree)
                      if path in ("routed", "pallas", "megakernel")
                      else None),
        "dtype_bytes": B,
        "payload_dim": d,
        "per_device": {
            "state_bytes": state_bytes,
            "delivery_bytes": int(delivery_bytes),
            "data_bytes": int(data_bytes),
            "temp_bytes": int(temp_bytes),
            "telemetry_bytes": int(telemetry_bytes),
            **extra_per_device,
            "total_bytes": int(total),
        },
        "argument_bytes": int(argument_bytes),
    }


def estimate_for_topology(topo, cfg, num_devices: int = 1,
                          telemetry_on: bool = True) -> Dict[str, Any]:
    """Exact-geometry variant for an already-built topology."""
    max_deg = int(topo.degree.max()) if topo.degree.size else 0
    return estimate_run_bytes(
        topo.kind, topo.num_nodes, cfg, num_devices,
        num_edges=int(topo.num_directed_edges), max_degree=max_deg,
        implicit_full=bool(topo.implicit_full), telemetry_on=telemetry_on,
    )


def estimate_build_host_bytes(
    kind: str,
    num_nodes: int,
    num_shards: int = 1,
    *,
    streamed: bool = False,
    memory_budget: Optional[int] = None,
    store_on_disk: bool = False,
    chunk_edges: Optional[int] = None,
    avg_degree: float = 8.0,
    m: int = 4,
    k: int = 6,
) -> int:
    """Predicted peak *host* RSS of topology construction, closed-form —
    the build-time twin of :func:`estimate_run_bytes` (which prices the
    run, after the build already survived).

    Materialized (``topology/builders.py`` + ``csr_from_edges``): the
    global undirected edge list, its symmetrized int64 src/dst pair, the
    dedup sort key, and the final CSR are all simultaneously live —
    ~40 bytes per directed edge plus ~16 per node.

    Streamed (``topology/stream.py``): one shard's pair set plus its
    finalize workspace (~24 bytes per *per-shard* directed edge), the
    bounded generator chunk, the spill buffer (``memory_budget``), and —
    unless slices go to ``store_dir`` files — the finished int32 slices
    (4 B/edge). Power-law adds its frozen endpoint list (4 B/edge); it
    is the one generator whose replay state is O(E).
    """
    from gossipprotocol_tpu.topology.registry import canonical_name

    n = int(num_nodes)
    e_dir, _ = edges_estimate(kind, n, avg_degree=avg_degree, m=m, k=k)
    if not streamed:
        return 40 * e_dir + 16 * n
    s = max(1, int(num_shards))
    if chunk_edges is None:
        from gossipprotocol_tpu.topology.stream import DEFAULT_CHUNK_EDGES

        chunk_edges = DEFAULT_CHUNK_EDGES
    if memory_budget is None:
        from gossipprotocol_tpu.topology.stream import DEFAULT_SPILL_BUDGET

        memory_budget = DEFAULT_SPILL_BUDGET
    total = 24 * (e_dir // s) + 32 * int(chunk_edges) + int(memory_budget)
    if not store_on_disk:
        total += 4 * e_dir + 8 * n  # finished slices stay resident
    if canonical_name(kind) == "power_law":
        total += 4 * e_dir
    return total


def build_rss_budget_bytes() -> Optional[int]:
    """``$GOSSIP_TPU_BUILD_RSS_BYTES`` — the host-memory admission budget
    for topology construction (None when unset)."""
    env = os.environ.get("GOSSIP_TPU_BUILD_RSS_BYTES")
    if not env:
        return None
    try:
        from gossipprotocol_tpu.topology.stream import parse_byte_size

        return parse_byte_size(env)
    except ValueError:
        raise CapacityError(
            f"bad $GOSSIP_TPU_BUILD_RSS_BYTES {env!r} (want bytes, "
            "K/M/G suffixes ok)")


def suggest_build_shards(kind: str, num_nodes: int, budget: int,
                         max_shards: int = 4096, **topo_params) -> Optional[int]:
    """Smallest power-of-two shard count whose *streamed* build estimate
    fits ``budget`` host bytes — the shard-count knob driven by build
    memory rather than HBM. None when even ``max_shards`` won't fit
    (the per-chunk and resident-slice floors are shard-independent)."""
    s = 1
    while s <= max_shards:
        if estimate_build_host_bytes(
                kind, num_nodes, s, streamed=True, **topo_params) <= budget:
            return s
        s *= 2
    return None


def device_capacity_bytes() -> Tuple[Optional[int], str]:
    """(per-device byte capacity, source). ``$GOSSIP_TPU_HBM_BYTES``
    wins (explicit admission-control budget); else the first device's
    ``memory_stats()['bytes_limit']``; else (None, 'unknown') — CPU
    backends have no accounting, and the preflight stays silent there."""
    env = os.environ.get("GOSSIP_TPU_HBM_BYTES")
    if env:
        try:
            return int(float(env)), "$GOSSIP_TPU_HBM_BYTES"
        except ValueError:
            raise CapacityError(
                f"bad $GOSSIP_TPU_HBM_BYTES {env!r} (want bytes)")
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"]), "memory_stats"
    except Exception:
        pass
    return None, "unknown"


def max_feasible_nodes(kind: str, cfg, num_devices: int,
                       capacity: int, *, safety: float = DEFAULT_SAFETY,
                       **topo_params) -> int:
    """Largest n whose predicted per-device total fits ``safety ×
    capacity`` at this geometry (binary search over the monotone model)."""
    budget = safety * capacity

    def fits(n: int) -> bool:
        doc = estimate_run_bytes(kind, n, cfg, num_devices, **topo_params)
        return doc["per_device"]["total_bytes"] <= budget

    lo = 1
    if not fits(lo):
        return 0
    hi = 2
    while fits(hi) and hi < 2 ** 40:
        lo, hi = hi, hi * 2
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if fits(mid):
            lo = mid
        else:
            hi = mid
    return lo


def preflight(topo, cfg, num_devices: int = 1, tel=None) -> Optional[Dict[str, Any]]:
    """Refuse an over-capacity run before any plan build.

    Returns the estimate doc (annotated with capacity) when capacity is
    known, None when it is not (CPU without the env override). Raises
    :class:`CapacityError` when the prediction exceeds the safety budget.
    """
    build_budget = build_rss_budget_bytes()
    if build_budget is not None and not topo.implicit_full:
        # the materialized build this topology would need (exact edge
        # count — the graph exists by now): warn when it exceeds the
        # host budget, so the operator learns the streamed build exists
        # before the next-size-up run OOMs the host
        e_dir = int(topo.num_directed_edges)
        mat = 40 * e_dir + 16 * int(topo.num_nodes)
        if mat > build_budget and not hasattr(topo, "csr_slice"):
            streamed_est = estimate_build_host_bytes(
                topo.kind, topo.num_nodes, max(1, int(num_devices)),
                streamed=True)
            msg = (
                f"host-build warning: a materialized {topo.kind}-"
                f"{topo.num_nodes} build peaks at ~{_fmt(mat)} host RSS, "
                f"over $GOSSIP_TPU_BUILD_RSS_BYTES={_fmt(build_budget)} "
                f"(streamed build would need ~{_fmt(streamed_est)}; use "
                f"--build streamed / --build-memory-budget)")
            print(msg, file=sys.stderr)
            if tel is not None:
                tel.note_resource("build_rss_warning", {
                    "materialized_bytes": mat,
                    "streamed_bytes": int(streamed_est),
                    "budget_bytes": int(build_budget),
                })
    capacity, source = device_capacity_bytes()
    if capacity is None:
        return None
    doc = estimate_for_topology(topo, cfg, num_devices)
    doc["capacity_bytes"] = capacity
    doc["capacity_source"] = source
    total = doc["per_device"]["total_bytes"]
    doc["capacity_fraction"] = round(total / capacity, 4)
    if tel is not None:
        tel.note_resource("capacity_plan", doc)
    if total > DEFAULT_SAFETY * capacity:
        feasible = max_feasible_nodes(
            topo.kind, cfg, num_devices, capacity,
        )
        lanes = doc.get("lanes", 1)
        what = (f"{lanes}-lane sweep over {topo.kind}-{topo.num_nodes}"
                if lanes > 1 else f"{topo.kind}-{topo.num_nodes}")
        hint = ("shrink the sweep (per-lane state is priced lanes x), "
                if lanes > 1 else "")
        raise CapacityError(
            f"predicted per-device footprint {_fmt(total)} exceeds "
            f"{int(DEFAULT_SAFETY * 100)}% of device capacity "
            f"{_fmt(capacity)} ({source}) for {what} "
            f"on {num_devices} device(s); max feasible n at this geometry "
            f"is ~{feasible} ({hint}add devices, shrink --payload-dim, or "
            f"raise $GOSSIP_TPU_HBM_BYTES if the budget is wrong)"
        )
    return doc


def _fmt(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return "?"


def main(argv=None) -> int:
    """``python -m gossipprotocol_tpu plan N TOPOLOGY [ALGO] [flags]``.

    Exit 0 when the request fits (or capacity is unknown and no budget
    was given), 1 when it is over capacity, 2 on bad input.
    """
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m gossipprotocol_tpu plan",
        description="Predict per-device HBM footprint and feasibility "
                    "before building anything.",
    )
    parser.add_argument("num_nodes", type=int)
    parser.add_argument("topology")
    parser.add_argument("algorithm", nargs="?", default="push-sum",
                        choices=["gossip", "push-sum"])
    parser.add_argument("--devices", type=int, default=1)
    parser.add_argument("--fanout", choices=["one", "all"], default="one")
    parser.add_argument("--delivery", default=None,
                        choices=["scatter", "invert", "routed", "pallas",
                                 "megakernel"])
    parser.add_argument("--payload-wire", default="f32",
                        choices=["f32", "bf16", "int8"],
                        help="price the sharded exchange slab at the "
                             "compressed wire format")
    parser.add_argument("--payload-dim", type=int, default=1)
    parser.add_argument("--workload", choices=["avg", "sgp", "gala"],
                        default="avg")
    parser.add_argument("--sgp-samples", type=int, default=16)
    parser.add_argument("--x64", action="store_true")
    parser.add_argument("--avg-degree", type=float, default=8.0)
    parser.add_argument("--hbm-bytes", type=float, default=None,
                        help="override per-device capacity (bytes)")
    parser.add_argument("--safety", type=float, default=DEFAULT_SAFETY)
    parser.add_argument("--json", action="store_true",
                        help="emit the raw estimate document")
    try:
        args = parser.parse_args(list(sys.argv[1:] if argv is None else argv))
    except SystemExit as e:
        return int(e.code or 0)
    if args.num_nodes < 1 or args.devices < 1:
        print("plan: num_nodes and --devices must be >= 1", file=sys.stderr)
        return 2
    if args.delivery == "megakernel" and args.devices > 1:
        print("plan: the round-loop megakernel is single-chip only — "
              "drop --devices", file=sys.stderr)
        return 2

    import jax.numpy as jnp

    from gossipprotocol_tpu.engine.driver import RunConfig

    try:
        cfg_kw: Dict[str, Any] = dict(
            algorithm=args.algorithm, fanout=args.fanout,
            payload_dim=args.payload_dim, workload=args.workload,
            sgp_samples=args.sgp_samples,
            dtype=jnp.float64 if args.x64 else jnp.float32,
        )
        if args.workload == "sgp":
            cfg_kw.update(fanout="all", predicate="global")
        elif args.workload == "gala":
            # smallest legal GALA shape for sizing: group count does not
            # change the byte estimate (data/state are per-row)
            cfg_kw.update(fanout="all", predicate="global", groups=2)
        if args.delivery is not None:
            cfg_kw["delivery"] = args.delivery
            if args.delivery == "megakernel":
                cfg_kw["fanout"] = "all"  # the only legal megakernel shape
        elif args.fanout == "all":
            cfg_kw["delivery"] = "routed"
        if args.payload_wire != "f32":
            if args.devices <= 1:
                raise CapacityError(
                    "--payload-wire prices the sharded exchange; it "
                    "needs --devices N > 1")
            cfg_kw["payload_wire"] = args.payload_wire
            cfg_kw["fanout"] = "all"  # the wire is the routed-push slab
            if cfg_kw.get("delivery") not in ("routed", "pallas"):
                cfg_kw["delivery"] = "routed"
        cfg = RunConfig(**cfg_kw)
        doc = estimate_run_bytes(
            args.topology, args.num_nodes, cfg, args.devices,
            avg_degree=args.avg_degree,
        )
    except (ValueError, CapacityError) as e:
        print(f"plan: {e}", file=sys.stderr)
        return 2

    if args.hbm_bytes is not None:
        capacity: Optional[int] = int(args.hbm_bytes)
        source = "--hbm-bytes"
    else:
        capacity, source = device_capacity_bytes()

    total = doc["per_device"]["total_bytes"]
    over = capacity is not None and total > args.safety * capacity
    if args.json:
        # pure JSON on stdout (pipeable into jq): the verdict rides in
        # the document and the exit code, never as trailing text
        import json as _json

        doc["capacity_bytes"] = capacity
        doc["capacity_source"] = source
        doc["safety"] = args.safety
        if doc["kind"] != "full":
            doc["build_host_bytes"] = {
                "materialized": estimate_build_host_bytes(
                    args.topology, args.num_nodes,
                    avg_degree=args.avg_degree),
                "streamed": estimate_build_host_bytes(
                    args.topology, args.num_nodes, args.devices,
                    streamed=True, avg_degree=args.avg_degree),
            }
        if capacity is not None:
            doc["capacity_fraction"] = round(total / capacity, 4)
            doc["max_feasible_nodes"] = max_feasible_nodes(
                args.topology, cfg, args.devices, capacity,
                safety=args.safety, avg_degree=args.avg_degree)
        doc["verdict"] = ("unknown" if capacity is None
                         else "over_capacity" if over else "fits")
        print(_json.dumps(doc, indent=2))
        return 1 if over else 0
    else:
        per = doc["per_device"]
        print(f"capacity plan: {args.algorithm} on "
              f"{doc['kind']}-{doc['num_nodes']}, "
              f"{doc['num_devices']} device(s), "
              f"delivery={doc['delivery_path']}, "
              f"d={doc['payload_dim']} x {doc['dtype_bytes']} B")
        print(f"  state:        {_fmt(per['state_bytes']):>12}/device")
        print(f"  delivery:     {_fmt(per['delivery_bytes']):>12}/device")
        hs = doc.get("hub_split")
        if hs:
            print(f"  hub split:    {hs['classes']} classes -> "
                  f"{hs['subclasses']} sub-classes "
                  f"(max degree ~{hs['max_degree']})")
        if per["data_bytes"]:
            print(f"  workload data:{_fmt(per['data_bytes']):>12}/device")
        print(f"  temp (est):   {_fmt(per['temp_bytes']):>12}/device")
        print(f"  telemetry:    {_fmt(per['telemetry_bytes']):>12}/device")
        if "pallas_vmem_scratch_bytes" in per:
            print(f"  vmem scratch: "
                  f"{_fmt(per['pallas_vmem_scratch_bytes']):>12}/kernel"
                  "  (advisory: VMEM, not HBM)")
        if "megakernel_vmem_bytes" in per:
            print(f"  vmem (fused): "
                  f"{_fmt(per['megakernel_vmem_bytes']):>12}/kernel"
                  "  (advisory: whole round resident, K-independent)")
        if "wire_exchange_bytes_per_round" in per:
            print(f"  exchange:     "
                  f"{_fmt(per['wire_exchange_bytes_per_round']):>12}"
                  f"/round/device  ({args.payload_wire} wire; f32 would "
                  f"be {_fmt(per['f32_exchange_bytes_per_round'])})")
        print(f"  total:        {_fmt(per['total_bytes']):>12}/device"
              f"  (argument bytes {_fmt(doc['argument_bytes'])})")
        if doc["kind"] != "full":
            mat_b = estimate_build_host_bytes(
                args.topology, args.num_nodes, avg_degree=args.avg_degree)
            str_b = estimate_build_host_bytes(
                args.topology, args.num_nodes, args.devices, streamed=True,
                avg_degree=args.avg_degree)
            print(f"  host build:   {_fmt(str_b):>12} streamed "
                  f"({args.devices} shard(s)) vs {_fmt(mat_b)} "
                  f"materialized")

    if capacity is None:
        print("  capacity:     unknown (no device memory accounting on "
              "this backend; pass --hbm-bytes or set $GOSSIP_TPU_HBM_BYTES)")
        return 0
    frac = total / capacity
    feasible = max_feasible_nodes(
        args.topology, cfg, args.devices, capacity, safety=args.safety,
        avg_degree=args.avg_degree)
    print(f"  capacity:     {_fmt(capacity)}/device ({source}), "
          f"safety {args.safety:.0%}")
    print(f"  max feasible n at this geometry: ~{feasible}")
    if over:
        print(f"  verdict: OVER CAPACITY ({frac:.0%} of device memory "
              f"> {args.safety:.0%} safety budget)")
        return 1
    print(f"  verdict: fits ({frac:.1%} of device memory)")
    return 0
