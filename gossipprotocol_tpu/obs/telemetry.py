"""Host-side span recorder: ``events.jsonl`` + Chrome-trace ``trace.json``.

A :class:`Telemetry` instance is created by the CLI when ``--telemetry-dir``
is set (or constructed directly by library callers, e.g. ``bench.py``) and
threaded to the engine via ``RunConfig.telemetry``.  Everywhere else in the
engine the accessor :func:`as_telemetry` turns ``None`` into the module
singleton :data:`NULL` so call sites never branch on presence.

Design constraints, in order:

* **Crash-durable**: every span/event is appended to ``events.jsonl`` the
  moment it closes (line-buffered), so a killed run still leaves a usable
  record; ``trace.json`` is additionally written on :meth:`Telemetry.close`
  and whenever ``write_trace`` is called.
* **Cheap**: one ``time.perf_counter`` pair and one ``json.dumps`` per
  span; no locks (the engine host loop is single-threaded), no buffering
  of unbounded history beyond the finished-span list needed for the trace.
* **Rollup-correct**: only *top-level* spans (depth 0) count toward the
  per-phase wall-time rollup, so nesting ``checkpoint_save`` inside a
  ``chunk`` span never double-counts.

Every line in ``events.jsonl`` carries ``"v": 1`` (see
:data:`gossipprotocol_tpu.utils.metrics.SCHEMA_VERSION`); readers must
treat an absent ``"v"`` as version 1 and refuse higher major versions.
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

# Events/trace share the metrics record schema version: both are "run
# telemetry records" and are read together by obs.report.
from gossipprotocol_tpu.utils.metrics import SCHEMA_VERSION

COUNTER_TOTAL_FIELDS = ("sent", "delivered", "dropped")

# Chrome-trace process ids: the run's own host spans live on pid 1; the
# serve daemon's request-lifecycle spans (serve/lifecycle.py) merge into
# the same trace.json on pid 2, so one Perfetto view shows the daemon
# timeline above the run's phases
TRACE_PID_RUN = 1
TRACE_PID_DAEMON = 2


def write_trace_doc(path: str, events: List[Dict[str, Any]]) -> str:
    """Atomically write a Perfetto-loadable Chrome-trace document. The
    one trace.json writer — Telemetry and serve/lifecycle.py both go
    through here so the envelope never drifts."""
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "gossipprotocol_tpu.obs",
                      "v": SCHEMA_VERSION},
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)
    return path


class TelemetryDirCollision(ValueError):
    """The target dir already holds another run's ``run.json``.

    Raised (collision="refuse", the default) instead of silently
    appending this run's events into a different run's record. The serve
    daemon passes collision="uniquify" to suffix the dir instead.
    """


def _manifest_run_id(out_dir: str):
    """The ``request_id`` of an existing ``run.json`` in ``out_dir``;
    None when there is no manifest; the string "<unreadable>" when one
    exists but cannot be parsed (treated as a different run — fail
    closed)."""
    path = os.path.join(out_dir, "run.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as fh:
            return json.load(fh).get("request_id") or "<unidentified>"
    except (OSError, json.JSONDecodeError):
        return "<unreadable>"


class _Span:
    """Handle yielded by :meth:`Telemetry.span`; ``set()`` adds attrs late."""

    __slots__ = ("name", "attrs", "depth", "t0", "start_s")

    def __init__(self, name: str, attrs: Dict[str, Any], depth: int, t0: float, start_s: float):
        self.name = name
        self.attrs = attrs
        self.depth = depth
        self.t0 = t0
        self.start_s = start_s

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)


class Telemetry:
    """Records host spans + run totals for one simulation run.

    Parameters
    ----------
    out_dir:
        Directory for ``events.jsonl`` / ``trace.json`` / ``run.json``
        (created if missing).
    counters:
        When True (the CLI default) the engine also folds on-device
        message counters through every chunk — a real (small) per-round
        cost.  ``bench.py`` passes False: spans and manifest only, with
        the compiled programs untouched.
    traces:
        Fold the per-round observatory trace buffer through every chunk
        (:mod:`gossipprotocol_tpu.obs.trace`) and append rows to
        ``trace.jsonl``.  ``None`` (default) follows ``counters``, so
        pre-trace constructions keep their exact compiled programs.
    trace_cap:
        Downsampling cap for ``trace.jsonl`` (rows before the stride
        doubles); ``None`` = ``$GOSSIP_TPU_TRACE_CAP`` or 4096.
    resources:
        Record the resource observatory (compiled-program
        cost/memory_analysis, host RSS + device-memory samples at span
        boundaries) into ``resources.json``.  ``None`` (default) = on:
        it is purely host-side, so it never perturbs a compiled program.
    attribution:
        Keep the sharded on-device counters *unreduced* per shard so the
        manifest can report shard-balance skew.  ``None`` (default)
        follows ``counters``; pass False to keep the counters-only
        compiled program literally pre-attribution.
    """

    enabled = True
    prediction = None  # obs.predict round prediction, set by the driver
    profile_dir = None  # jax.profiler trace dir when --profile-dir is set
    sweep = None  # sweep rollup (lanes, per-lane records), set by _drive_sweep
    admission = None  # serve admission verdict doc, set by the CLI/daemon

    def __init__(self, out_dir: str, *, counters: bool = True,
                 traces: Optional[bool] = None,
                 trace_cap: Optional[int] = None,
                 resources: Optional[bool] = None,
                 attribution: Optional[bool] = None,
                 run_id: Optional[str] = None,
                 collision: str = "refuse"):
        self.dir = os.path.abspath(out_dir)
        self.run_id = run_id
        if run_id is not None:
            # collision guard: a dir already holding a DIFFERENT run's
            # manifest must not silently accumulate this run's events.
            # Only guarded when the caller identifies the run (the serve
            # daemon always does); anonymous CLI runs keep the historical
            # overwrite-on-reuse behavior.
            existing = _manifest_run_id(self.dir)
            if existing is not None and existing != run_id:
                if collision == "uniquify":
                    base, n = self.dir, 2
                    while True:
                        cand = f"{base}-{n}"
                        ex = _manifest_run_id(cand)
                        if ex is None or ex == run_id:
                            self.dir = cand
                            break
                        n += 1
                else:
                    raise TelemetryDirCollision(
                        f"telemetry dir {self.dir} already holds run.json "
                        f"from a different run (request_id {existing!r}, "
                        f"this run is {run_id!r}) — pick a fresh dir, or "
                        "pass collision='uniquify'")
        os.makedirs(self.dir, exist_ok=True)
        self.counters_on = bool(counters)
        self.traces_on = bool(counters if traces is None else traces)
        self.resources_on = bool(True if resources is None else resources)
        self.attribution_on = bool(
            self.counters_on if attribution is None else attribution)
        self.shard_totals = None  # np.int64 [num_shards, 3] when attributed
        if self.resources_on:
            from gossipprotocol_tpu.obs.resources import ResourceRecorder

            self._resources = ResourceRecorder()
        else:
            self._resources = None
        self._trace_cap = trace_cap
        self._trace_writer = None
        self._t0 = time.perf_counter()
        self._epoch0 = time.time()
        self._depth = 0
        self._finished: List[Dict[str, Any]] = []
        self._closed = False
        self.totals: Dict[str, int] = {k: 0 for k in COUNTER_TOTAL_FIELDS}
        self.max_mass_drift_ulps = 0.0
        self.max_w_drift_ulps = 0.0
        self._events = open(os.path.join(self.dir, "events.jsonl"), "a", buffering=1)
        self._emit({"kind": "start", "epoch_s": self._epoch0, "pid": os.getpid()})

    # ---------------------------------------------------------------- spans

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[_Span]:
        """Time a host-side phase; nested use is fine (depth is recorded)."""
        sp = _Span(name, dict(attrs), self._depth, time.perf_counter(), 0.0)
        sp.start_s = sp.t0 - self._t0
        self._depth += 1
        try:
            yield sp
        finally:
            self._depth -= 1
            dur = time.perf_counter() - sp.t0
            rec = {
                "kind": "span",
                "name": sp.name,
                "start_s": round(sp.start_s, 6),
                "dur_s": round(dur, 6),
                "depth": sp.depth,
            }
            if sp.attrs:
                rec["attrs"] = sp.attrs
            self._finished.append(rec)
            self._emit(rec)
            if sp.depth == 0 and self._resources is not None:
                self._resources.sample(sp.name)

    def mark_span(self, name: str, start_s: float, dur_s: float,
                  **attrs: Any) -> None:
        """Record an already-elapsed interval as a *nested* span (depth 1).

        Used for intervals measured outside the ``span()`` context — the
        jax.profiler trace wraps the whole run, so recording it at depth
        0 would double-count every phase in the rollup.
        """
        rec: Dict[str, Any] = {
            "kind": "span",
            "name": name,
            "start_s": round(start_s, 6),
            "dur_s": round(dur_s, 6),
            "depth": 1,
        }
        if attrs:
            rec["attrs"] = attrs
        self._finished.append(rec)
        self._emit(rec)

    def event(self, name: str, **attrs: Any) -> None:
        """Record an instant (zero-duration) host event."""
        rec = {
            "kind": "event",
            "name": name,
            "start_s": round(time.perf_counter() - self._t0, 6),
        }
        if attrs:
            rec["attrs"] = attrs
        self._finished.append(rec)
        self._emit(rec)

    def metric(self, record: Dict[str, Any]) -> None:
        """Mirror a per-chunk metrics record into ``events.jsonl``."""
        self._emit({"kind": "metric", "rec": record})

    # ------------------------------------------------------------- counters

    def add_counters(self, sent: int, delivered: int, dropped: int) -> None:
        self.totals["sent"] += int(sent)
        self.totals["delivered"] += int(delivered)
        self.totals["dropped"] += int(dropped)

    def note_mass_drift(self, s_ulps: float, w_ulps: float) -> None:
        self.max_mass_drift_ulps = max(self.max_mass_drift_ulps, float(s_ulps))
        self.max_w_drift_ulps = max(self.max_w_drift_ulps, float(w_ulps))

    def add_shard_counters(self, totals) -> None:
        """Accumulate one chunk's per-shard counter partials — an int64
        ``[num_shards, 3]`` array whose sum over shards the driver has
        already asserted equals the reduced totals bitwise."""
        import numpy as np

        totals = np.asarray(totals, dtype=np.int64)
        if self.shard_totals is None:
            self.shard_totals = totals.copy()
        else:
            self.shard_totals = self.shard_totals + totals

    def shard_balance(self) -> Optional[Dict[str, Any]]:
        """Per-shard attribution summary for the manifest; None when the
        run was single-device or attribution was off."""
        if self.shard_totals is None:
            return None
        totals = self.shard_totals
        sent = totals[:, 0].astype(float)
        mean = float(sent.mean()) if sent.size else 0.0
        doc: Dict[str, Any] = {
            "num_shards": int(totals.shape[0]),
            "sent": [int(x) for x in totals[:, 0]],
            "delivered": [int(x) for x in totals[:, 1]],
            "dropped": [int(x) for x in totals[:, 2]],
            "sent_skew_max_over_mean": (
                round(float(sent.max()) / mean, 6) if mean > 0 else None
            ),
        }
        if self._resources is not None:
            exch = self._resources.notes.get("exchange_bytes_per_round")
            if isinstance(exch, (int, float)) and totals.shape[0] > 0:
                doc["edge_share_bytes_per_round_per_shard"] = int(
                    exch / totals.shape[0])
        return doc

    # -------------------------------------------------------------- resources

    def record_compiled(self, label: str, compiled, **attrs: Any) -> None:
        """XLA cost/memory introspection of a freshly compiled program."""
        if self._resources is not None:
            self._resources.record_compiled(label, compiled, **attrs)

    def sample_resources(self, tag: str) -> None:
        if self._resources is not None:
            self._resources.sample(tag)

    def note_resource(self, key: str, value: Any) -> None:
        if self._resources is not None:
            self._resources.note(key, value)

    def write_resources(self) -> Optional[str]:
        if self._resources is None:
            return None
        from gossipprotocol_tpu.obs.resources import write_resources

        return write_resources(self.dir, self._resources)

    # ---------------------------------------------------------------- traces

    def add_trace_rows(self, start_round: int, rows) -> None:
        """Append one chunk's per-round trace rows (rounds
        ``start_round+1 ..``) to ``trace.jsonl`` — no-op with traces off."""
        if not self.traces_on or self._closed:
            return
        from gossipprotocol_tpu.obs.trace import TraceWriter

        if self._trace_writer is None:
            self._trace_writer = TraceWriter(
                os.path.join(self.dir, "trace.jsonl"), cap=self._trace_cap)
        stride0 = self._trace_writer.stride
        self._trace_writer.add(start_round, rows)
        if self._trace_writer.stride != stride0:
            self.event("trace_downsample",
                       stride=self._trace_writer.stride,
                       rows_written=self._trace_writer.rows_written)

    def trace_summary(self) -> Optional[Dict[str, int]]:
        if self._trace_writer is None:
            return None
        return self._trace_writer.summary()

    # -------------------------------------------------------------- outputs

    def wall_s(self) -> float:
        return time.perf_counter() - self._t0

    def phase_rollup(self) -> Dict[str, Dict[str, float]]:
        """Aggregate top-level spans by name: ``{name: {count, total_s}}``.

        Depth > 0 spans are excluded so nested phases (a checkpoint save
        inside a chunk) are counted exactly once, under their parent.
        """
        out: Dict[str, Dict[str, float]] = {}
        for rec in self._finished:
            if rec["kind"] != "span" or rec["depth"] != 0:
                continue
            agg = out.setdefault(rec["name"], {"count": 0, "total_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += rec["dur_s"]
        for agg in out.values():
            agg["total_s"] = round(agg["total_s"], 6)
        return out

    def write_trace(self, path: Optional[str] = None) -> str:
        """Write Chrome trace event format (Perfetto / chrome://tracing)."""
        path = path or os.path.join(self.dir, "trace.json")
        events = []
        for rec in self._finished:
            ev: Dict[str, Any] = {
                "name": rec["name"],
                "cat": "host",
                "pid": TRACE_PID_RUN,
                "tid": 1 + rec.get("depth", 0),
                "ts": round(rec["start_s"] * 1e6, 3),
            }
            if rec["kind"] == "span":
                ev["ph"] = "X"
                ev["dur"] = round(rec["dur_s"] * 1e6, 3)
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            if rec.get("attrs"):
                ev["args"] = rec["attrs"]
            events.append(ev)
        return write_trace_doc(path, events)

    def close(self) -> None:
        """Write ``trace.json`` and close ``events.jsonl``; idempotent."""
        if self._closed:
            return
        self._closed = True
        try:
            if self._resources is not None:
                self._resources.sample("close")
                self.write_resources()
            self.write_trace()
            self._emit({"kind": "end", "wall_s": round(self.wall_s(), 6)})
        finally:
            if self._trace_writer is not None:
                self._trace_writer.close()
            self._events.close()

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def _emit(self, rec: Dict[str, Any]) -> None:
        if self._events.closed:
            return
        rec = {"v": SCHEMA_VERSION, **rec}
        self._events.write(json.dumps(rec) + "\n")


class NullTelemetry:
    """No-op stand-in used whenever telemetry is off.

    Mirrors the full :class:`Telemetry` surface so engine code is written
    once, unconditionally.  ``counters_on`` is False, which is what keeps
    the compiled chunk programs bitwise identical to a telemetry-free
    build (the counter fold is never installed).
    """

    enabled = False
    counters_on = False
    traces_on = False
    resources_on = False
    attribution_on = False
    prediction = None
    profile_dir = None
    sweep = None
    admission = None
    run_id = None
    shard_totals = None
    dir = None

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[_Span]:
        yield _NULL_SPAN

    def mark_span(self, name: str, start_s: float, dur_s: float,
                  **attrs: Any) -> None:
        pass

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def metric(self, record: Dict[str, Any]) -> None:
        pass

    def add_counters(self, sent: int, delivered: int, dropped: int) -> None:
        pass

    def note_mass_drift(self, s_ulps: float, w_ulps: float) -> None:
        pass

    def add_shard_counters(self, totals) -> None:
        pass

    def shard_balance(self) -> Optional[Dict[str, Any]]:
        return None

    def record_compiled(self, label: str, compiled, **attrs: Any) -> None:
        pass

    def sample_resources(self, tag: str) -> None:
        pass

    def note_resource(self, key: str, value: Any) -> None:
        pass

    def write_resources(self) -> Optional[str]:
        return None

    def add_trace_rows(self, start_round: int, rows) -> None:
        pass

    def trace_summary(self) -> Optional[Dict[str, int]]:
        return None

    def wall_s(self) -> float:
        return 0.0

    def phase_rollup(self) -> Dict[str, Dict[str, float]]:
        return {}

    def write_trace(self, path: Optional[str] = None) -> Optional[str]:
        return None

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullTelemetry":
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


class _NullSpan:
    def set(self, **attrs: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()
NULL = NullTelemetry()


def as_telemetry(obj: Any) -> Any:
    """``RunConfig.telemetry`` accessor: ``None`` -> the no-op singleton."""
    return NULL if obj is None else obj
