"""Live monitoring of a telemetry dir while the run is still going.

``python -m gossipprotocol_tpu watch DIR`` tails what the run has
written so far — ``events.jsonl`` and ``trace.jsonl`` grow line by line,
``run.json`` lands at the end — and refreshes a compact status frame
every ``--interval`` seconds: current round, residual, converged
fraction, message totals, and any anomaly the partial records already
prove. On a tty each refresh clears the screen; piped output gets
separator-delimited frames instead (so CI logs stay readable).

Exits 0 as soon as the manifest reports a result (the run finished) or
after ``--max-frames`` refreshes; exits 2 when DIR is not a directory.
A dir that has no telemetry *yet* is not an error — watch waits for it.

``watch --queue-dir D`` is the fleet mode: instead of one run's
telemetry it tails a serve daemon's queue dir — queue depth, what each
worker is currently executing (request id + last published round), the
SLO burn rates from :mod:`gossipprotocol_tpu.obs.slo`, and the
daemon-level anomaly rules. The fleet frame never "finishes" (a daemon
is long-lived); it exits only via ``--max-frames`` or ^C.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Dict, List, Optional, TextIO

from gossipprotocol_tpu.obs.anomaly import anomaly_flags, daemon_flags
from gossipprotocol_tpu.obs.report import (
    ReportError,
    _metric_recs,
    load_telemetry_dir,
    sparkline,
)

INTERVAL_DEFAULT = 2.0

# journal phases that mean "a worker is executing this request now"
_RUNNING_PHASES = ("started", "batched")


def _frame(data: Dict[str, Any], out: TextIO) -> bool:
    """Write one status frame; returns True when the run is finished."""
    manifest = data["manifest"]
    events = data["events"]
    trace = data.get("trace") or []
    metrics = _metric_recs(events)

    result = (manifest or {}).get("result")
    chunked = [r for r in metrics if "round" in r]
    last = chunked[-1] if chunked else {}
    rid = (manifest or {}).get("request_id")
    if rid:
        out.write(f"request {rid} (daemon-executed)\n")
    rnd = (result or {}).get("rounds", last.get("round", 0))
    out.write(f"round {rnd}")
    if result is not None:
        out.write(
            f"  FINISHED: "
            f"{'converged' if result.get('converged') else 'NOT converged'}"
            f" in {result.get('wall_ms', 0.0):.1f} ms\n"
        )
    else:
        out.write("  (running)\n")
    sweep = (manifest or {}).get("sweep")
    if sweep:
        # finished sweep: the manifest rollup is authoritative
        out.write(
            f"sweep     lanes converged "
            f"{sweep.get('converged_lanes', 0)}/{sweep.get('lanes', '?')}"
            f"  rounds p50 {sweep.get('rounds_p50', 0):.0f}"
            f" / p95 {sweep.get('rounds_p95', 0):.0f}\n")
    elif "lanes" in last:
        # still running: the latest chunk record carries the lane tally
        out.write(
            f"sweep     lanes converged "
            f"{last.get('lanes_done', 0)}/{last['lanes']}"
            f"  slowest lane at round {last.get('round', 0)}"
            f" (fastest frozen at {last.get('rounds_min', 0)})\n")
    alive = last.get("alive")
    if alive:
        out.write(
            f"alive {alive}  converged {last.get('converged', 0)}/{alive}\n")
    residuals = [
        r["residual"] for r in trace
        if isinstance(r.get("residual"), (int, float))
        and r["residual"] == r["residual"]
    ]
    if residuals:
        out.write(
            f"residual  {sparkline(residuals)}  {residuals[-1]:.3e}\n")
    # live sentinel status: trips/quarantines the partial record already
    # shows (the manifest rollup only lands when the run finishes)
    trips = [r for r in metrics if r.get("event") == "sentinel_trip"]
    quars = [r for r in metrics if r.get("event") == "quarantine"]
    if trips or quars:
        last_t = trips[-1] if trips else {}
        qn = sum(int(r.get("nodes", 0)) for r in quars)
        out.write(
            f"sentinel  {len(trips)} trip(s)"
            + (f", last {last_t.get('cause', '?')} at round "
               f"{last_t.get('round', '?')}" if trips else "")
            + (f"; quarantined {qn} node(s)" if quars else "")
            + "\n"
        )
    counters = (manifest or {}).get("counters")
    if counters:
        out.write(
            f"messages  sent={counters.get('sent', 0)}"
            f" delivered={counters.get('delivered', 0)}"
            f" dropped={counters.get('dropped', 0)}\n"
        )
    resources = data.get("resources")
    if resources:
        host = resources.get("host") or {}
        peak = host.get("peak_rss_bytes")
        if isinstance(peak, (int, float)):
            samples = resources.get("samples") or []
            cur = next(
                (s["rss_bytes"] for s in reversed(samples)
                 if isinstance(s.get("rss_bytes"), (int, float))),
                host.get("rss_bytes"),
            )
            out.write(
                f"host RSS  {cur / 2**20:.0f} MiB"
                f" (peak {peak / 2**20:.0f} MiB)\n"
                if isinstance(cur, (int, float))
                else f"host RSS  peak {peak / 2**20:.0f} MiB\n"
            )
    flags = anomaly_flags(manifest, metrics, trace)
    # a still-running dir has no manifest by design — not an anomaly yet
    flags = [f for f in flags if not f.startswith("run.json missing")
             or result is not None]
    if flags:
        for f in flags:
            out.write(f"! {f}\n")
    else:
        out.write("anomalies: none\n")
    return result is not None


def _fleet_frame(paths, out: TextIO) -> None:
    """One frame of the fleet view over a serve queue dir."""
    from gossipprotocol_tpu.obs import slo as slo_mod
    from gossipprotocol_tpu.serve import journal as journal_mod
    from gossipprotocol_tpu.serve import lifecycle as lifecycle_mod

    states = journal_mod.replay(journal_mod.read_journal(paths.journal))
    running = [st for st in states.values()
               if st.phase in _RUNNING_PHASES]
    pending = [st for st in states.values()
               if not st.terminal and st.phase not in _RUNNING_PHASES]
    try:
        incoming = len([f for f in os.listdir(paths.incoming)
                        if f.endswith(".json")])
    except OSError:
        incoming = 0
    out.write(
        f"queue depth {len(running) + len(pending) + incoming}"
        f" ({len(running)} running, {len(pending)} pending"
        + (f", {incoming} incoming" if incoming else "")
        + ")\n")
    for st in sorted(running, key=lambda s: s.id):
        prog = lifecycle_mod.request_progress(paths, st) or {}
        rnd = prog.get("round")
        phase = prog.get("phase")
        out.write(
            f"worker  {st.id}"
            + (f"  round {rnd}" if rnd is not None else "")
            + (f"  phase {phase}" if phase else "  (starting)")
            + "\n")
    done = sum(1 for st in states.values() if st.terminal)
    out.write(f"settled {done} request(s)\n")
    slo_mod.render_slos(
        slo_mod.evaluate_slos(states.values()), out)
    flags = daemon_flags(states)
    if flags:
        for f in flags:
            out.write(f"! {f}\n")
    else:
        out.write("anomalies: none\n")


def _fleet_loop(queue_dir: str, interval: float,
                max_frames: Optional[int]) -> int:
    from gossipprotocol_tpu.serve import journal as journal_mod

    if not os.path.isdir(queue_dir):
        print(f"watch: {queue_dir!r} is not a directory", file=sys.stderr)
        return 2
    paths = journal_mod.QueuePaths(os.path.abspath(queue_dir))
    out = sys.stdout
    tty = out.isatty()
    frames = 0
    while True:
        if tty:
            out.write("\x1b[2J\x1b[H")
        else:
            out.write(f"--- frame {frames + 1} ---\n")
        out.write(f"fleet {queue_dir}  [{time.strftime('%H:%M:%S')}]\n")
        _fleet_frame(paths, out)
        out.flush()
        frames += 1
        if max_frames is not None and frames >= max_frames:
            return 0
        time.sleep(interval)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(
            "usage: python -m gossipprotocol_tpu watch TELEMETRY_DIR "
            "[--interval S] [--max-frames N]\n"
            "       python -m gossipprotocol_tpu watch --queue-dir D "
            "[--interval S] [--max-frames N]",
            file=sys.stderr if not argv else sys.stdout,
        )
        return 0 if argv else 2
    interval = INTERVAL_DEFAULT
    max_frames: Optional[int] = None
    queue_dir: Optional[str] = None
    paths: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("--interval", "--max-frames", "--queue-dir"):
            if i + 1 >= len(argv):
                print(f"watch: {a} needs a value", file=sys.stderr)
                return 2
            try:
                if a == "--interval":
                    interval = max(0.05, float(argv[i + 1]))
                elif a == "--max-frames":
                    max_frames = int(argv[i + 1])
                else:
                    queue_dir = argv[i + 1]
            except ValueError:
                print(f"watch: bad {a} {argv[i + 1]!r}", file=sys.stderr)
                return 2
            i += 2
        else:
            paths.append(a)
            i += 1
    if queue_dir is not None:
        return _fleet_loop(queue_dir, interval, max_frames)
    if not paths:
        print("watch: missing TELEMETRY_DIR", file=sys.stderr)
        return 2
    path = paths[0]
    if not os.path.isdir(path):
        print(f"watch: {path!r} is not a directory", file=sys.stderr)
        return 2

    out = sys.stdout
    tty = out.isatty()
    frames = 0
    while True:
        try:
            data = load_telemetry_dir(path)
        except ReportError:
            data = None  # nothing written yet — keep waiting
        if tty:
            out.write("\x1b[2J\x1b[H")  # clear screen, home cursor
        else:
            out.write(f"--- frame {frames + 1} ---\n")
        out.write(f"watch {path}  [{time.strftime('%H:%M:%S')}]\n")
        finished = False
        if data is None:
            out.write("(no telemetry yet — waiting for the run to start)\n")
        else:
            finished = _frame(data, out)
        out.flush()
        frames += 1
        if finished:
            return 0
        if max_frames is not None and frames >= max_frames:
            return 0
        time.sleep(interval)
