"""Declarative serving SLOs + burn rates over the request journal.

An :class:`SLOSpec` names a per-request indicator, a threshold the
indicator must stay under, and an objective — the fraction of requests
that must meet it. :func:`evaluate_slos` folds replayed journal states
into one :class:`SLOStatus` per spec with the classic burn rate:

    burn = (bad / total) / (1 - objective)

burn 1.0 = exactly spending the error budget; > 1.0 = breached. A spec
with no measurable requests yet reports ``total == 0`` and burn 0 — an
idle daemon is never "breached".

Indicators (all derived from the journal, no live daemon needed):

``admission_latency_s``   accepted → admission verdict
``queue_wait_s``          accepted → first worker start (or refusal)
``prediction_ratio``      actual rounds / admission-time predicted
                          rounds — the serving-side closure of
                          obs/predict.py's spectral bound; measurable
                          only for finished requests whose ``admitted``
                          journal event carried ``predicted_rounds``
                          (the supervisor stamps it at admission).

The daemon-level anomaly rules (queue saturation, prediction-ratio
blowout, retry storm) build on these indicators in
:func:`gossipprotocol_tpu.obs.anomaly.daemon_flags`; the fleet
``watch --queue-dir`` mode renders both live.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional

from gossipprotocol_tpu.serve import journal as journal_mod


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One serving objective: ``objective`` of requests keep
    ``indicator`` at or under ``threshold``."""

    name: str
    indicator: str      # admission_latency_s | queue_wait_s | prediction_ratio
    threshold: float
    objective: float    # target good fraction, e.g. 0.95
    description: str = ""


DEFAULT_SLOS = (
    SLOSpec("admission_latency", "admission_latency_s", 2.0, 0.99,
            "accepted -> admission verdict within 2s for 99%"),
    SLOSpec("queue_wait", "queue_wait_s", 30.0, 0.95,
            "accepted -> worker start within 30s for 95%"),
    SLOSpec("prediction_ratio", "prediction_ratio", 8.0, 0.95,
            "actual rounds within 8x the admission-time prediction "
            "for 95% of finished requests"),
)


@dataclasses.dataclass
class SLOStatus:
    spec: SLOSpec
    good: int
    bad: int

    @property
    def total(self) -> int:
        return self.good + self.bad

    @property
    def bad_fraction(self) -> float:
        return self.bad / self.total if self.total else 0.0

    @property
    def burn_rate(self) -> float:
        budget = 1.0 - self.spec.objective
        if budget <= 0.0:
            return float("inf") if self.bad else 0.0
        return round(self.bad_fraction / budget, 3)

    @property
    def breached(self) -> bool:
        return self.burn_rate > 1.0


def prediction_ratio(st: journal_mod.RequestState) -> Optional[float]:
    """Actual rounds over admission-predicted rounds; None when either
    side is missing (request not finished, prediction not stamped)."""
    admitted = st.first("admitted")
    if admitted is None:
        return None
    predicted = admitted.get("predicted_rounds")
    if not isinstance(predicted, (int, float)) or predicted <= 0:
        return None
    final = st.first("finished") or st.first("over_budget")
    if final is None:
        return None
    rounds = final.get("rounds")
    if not isinstance(rounds, (int, float)):
        return None
    return round(float(rounds) / float(predicted), 3)


def indicator_value(st: journal_mod.RequestState,
                    indicator: str) -> Optional[float]:
    if indicator == "admission_latency_s":
        return st.admission_latency_s
    if indicator == "queue_wait_s":
        return st.queue_wait_s
    if indicator == "prediction_ratio":
        return prediction_ratio(st)
    raise ValueError(f"unknown SLO indicator {indicator!r}")


def evaluate_slos(states: Iterable[journal_mod.RequestState],
                  specs: Iterable[SLOSpec] = DEFAULT_SLOS
                  ) -> List[SLOStatus]:
    """One status per spec over every measurable request. Requests whose
    indicator is not (yet) derivable — still queued, never admitted, old
    journals without the stamped prediction — are skipped, not counted
    bad: the burn rate only spends budget on *proven* misses."""
    states = list(states)
    out: List[SLOStatus] = []
    for spec in specs:
        good = bad = 0
        for st in states:
            value = indicator_value(st, spec.indicator)
            if value is None:
                continue
            if value <= spec.threshold:
                good += 1
            else:
                bad += 1
        out.append(SLOStatus(spec, good, bad))
    return out


def render_slos(statuses: List[SLOStatus], out) -> None:
    """The fleet watch frame's SLO lines."""
    for s in statuses:
        line = (f"slo {s.spec.name:<18} "
                f"{s.good}/{s.total} within {s.spec.threshold:g}"
                f"{'s' if s.spec.indicator.endswith('_s') else 'x'}"
                f"  burn {s.burn_rate:.2f}x")
        if s.breached:
            line += "  BREACHED"
        out.write(line + "\n")


def slo_doc(statuses: List[SLOStatus]) -> List[Dict[str, Any]]:
    """JSON-able form (the /status and watch --json surfaces)."""
    return [{
        "name": s.spec.name,
        "indicator": s.spec.indicator,
        "threshold": s.spec.threshold,
        "objective": s.spec.objective,
        "good": s.good,
        "bad": s.bad,
        "burn_rate": s.burn_rate,
        "breached": s.breached,
    } for s in statuses]
