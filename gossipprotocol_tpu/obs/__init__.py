"""Unified run telemetry (SURVEY.md §5.5 grown up).

The reference's observability surface is one ``Stopwatch`` and three
``printfn`` lines (``Program.fs:35,55,198,204``); the system around our
reproduction — sharded routed delivery, fault schedules, the parallel
plan compiler, self-healing repair — is far too complex to debug from a
single "Convergence Time" line. This package makes every run optionally
self-describing:

* :mod:`~gossipprotocol_tpu.obs.telemetry` — host-side spans streamed to
  ``events.jsonl`` plus a Chrome-trace ``trace.json`` (Perfetto-loadable),
  complementing ``--profile-dir``'s device-level ``jax.profiler`` trace;
* :mod:`~gossipprotocol_tpu.obs.counters` — on-device message counters
  folded through the chunk scan (sent / delivered / dropped, push-sum
  mass drift in ULPs), riding *alongside* protocol state so convergence
  stays bitwise identical with telemetry on;
* :mod:`~gossipprotocol_tpu.obs.manifest` — ``run.json``: the full
  config, versions, digests, resume lineage, and per-phase wall-time
  rollup that makes any BENCH/MULTICHIP number reproducible;
* :mod:`~gossipprotocol_tpu.obs.report` — ``python -m gossipprotocol_tpu
  report DIR`` renders a telemetry dir for humans;
* :mod:`~gossipprotocol_tpu.obs.resources` — the resource observatory:
  XLA ``cost_analysis()``/``memory_analysis()`` per compiled chunk
  program, host-RSS/device-memory samples at span boundaries, per-shard
  counter attribution (``shard_balance``) — persisted as
  ``resources.json`` beside the manifest;
* :mod:`~gossipprotocol_tpu.obs.capacity` — the analytic HBM capacity
  planner behind the ``plan`` subcommand and the CLI's over-capacity
  preflight (refuse before any plan build), validated against
  ``memory_analysis()``.

Zero-cost contract: with ``RunConfig.telemetry`` unset every engine code
path sees :class:`NullTelemetry` (no-op spans, ``counters_on=False``), so
the compiled chunk programs — and therefore results and metrics records —
are bitwise identical to a build without this package.
"""

from gossipprotocol_tpu.obs.manifest import write_manifest  # noqa: F401
from gossipprotocol_tpu.obs.telemetry import (  # noqa: F401
    NullTelemetry,
    Telemetry,
    as_telemetry,
)
