"""Anomaly rules over a run's telemetry records.

:func:`anomaly_flags` is the one entry point: given the manifest, the
chunk-granularity metric records, and (optionally) the per-round trace
rows from ``trace.jsonl``, it returns human-readable flags for every
condition the records can *prove* — no heuristics that fire on healthy
runs, because CI asserts ``anomalies: none`` on clean chaos smokes.

Rule groups:

* **record rules** (manifest + metrics — the original ``report`` checks,
  texts unchanged): did-not-converge, gossip stall, w-underflow,
  link-loss drops, mass drift beyond ULP tolerance, missing manifest;
* **counter rules**: sent ≠ delivered + dropped on runs where the
  identity must hold (push-sum without churn — gossip legitimately
  suppresses receiver-side, and dead receivers ignore shares);
* **budget rules**: the run tripped an enforced ``round_budget`` (the
  driver's structured ``over_budget`` record), or overshot the analytic
  prediction's ``budget_factor × predicted`` bound;
* **trace rules** (need ``trace.jsonl``, gated on *not converged* so a
  finished run never trips them): residual plateau (stall) and residual
  growth (divergence) over the last :data:`TRACE_WINDOW` trace rows.

:func:`daemon_flags` is the serve-daemon counterpart over replayed
journal states (queue saturation, prediction-ratio blowout, retry
storm) — same contract: rules only fire on provable conditions, so a
healthy daemon smoke renders ``anomalies: none``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

# trace rules look at the last this-many trace rows
TRACE_WINDOW = 8
# plateau: relative residual span across the window below this
STALL_REL_SPAN = 1e-3
# divergence: last residual at least this factor above the window's first
DIVERGE_FACTOR = 2.0
# mass drift beyond this many ULPs is flagged (matches the driver's own
# loss-window bookkeeping slack)
DRIFT_ULP_TOL = 64.0
# shard attribution: max/mean sent skew beyond this factor is flagged —
# a balanced partition sits near 1.0, and padding rows send nothing, so
# a sustained 1.5x means one shard owns disproportionate edge work
SHARD_SKEW_FACTOR = 1.5
# ... but only once enough messages flowed for the ratio to mean
# anything (tiny smoke runs legitimately skew on integer granularity)
SHARD_SKEW_MIN_SENT = 10_000
# daemon rules: retries across the journal before the storm rule fires
# (one request's in-policy retries — at most retry_attempts-1 = 2 by
# default — never trip it)
RETRY_STORM_MIN = 3
# ... and how far past its admission-time prediction a finished request
# must run (matches obs.predict.BUDGET_FACTOR: within the auto budget
# is healthy by definition)
PREDICTION_BLOWOUT_FACTOR = 8.0

# pinned daemon-rule message heads (tests and CI grep these verbatim)
MSG_QUEUE_SATURATED = (
    "queue SATURATED: {n} request(s) refused queue-full — raise "
    "--max-queue or add workers")
MSG_PREDICTION_BLOWOUT = (
    "prediction blowout: {rid} ran {rounds} rounds, {ratio:.1f}x its "
    "admission-time prediction of {predicted}")
MSG_RETRY_STORM = (
    "retry STORM: {n} infra retries across {m} request(s) — "
    "accelerator runtime flapping")


def _finite(x: Any) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(x)


def _record_flags(manifest: Optional[Dict[str, Any]],
                  metrics: List[Dict[str, Any]]) -> List[str]:
    flags: List[str] = []
    result = (manifest or {}).get("result")
    if (result is not None and not result.get("converged", True)
            and not (manifest or {}).get("sweep")):
        # sweep runs get the lane-resolved flag from _sweep_flags instead
        flags.append("DID NOT CONVERGE within the round budget")
    if any(r.get("stalled") for r in metrics):
        flags.append("gossip STALLED (live spreaders exhausted before quorum)")
    peak_underflow = max((r.get("w_underflow", 0) or 0 for r in metrics),
                         default=0)
    if peak_underflow:
        flags.append(
            f"push-sum w-underflow: up to {peak_underflow} alive rows hit "
            "w == 0 (dry-spell wall — consider f64)"
        )
    counters = (manifest or {}).get("counters")
    if counters and counters.get("dropped", 0) > 0:
        flags.append(f"{counters['dropped']} messages dropped by link loss")
    drift = (manifest or {}).get("max_mass_drift_ulps")
    wdrift = (manifest or {}).get("max_w_drift_ulps")
    # a lossy --payload-wire deliberately rounds edge shares on the
    # sharded exchange, so drift there is the documented cost of the
    # knob, not an anomaly — same gating as churn on the counter rule.
    # Likewise a value-fault/quarantine run: the injected corruption and
    # the containment kill both displace mass by construction, which is
    # the sentinel's story (its own rule below), not honest drift
    wire = (manifest or {}).get("config", {}).get("payload_wire", "f32")
    displaced = (
        ((manifest or {}).get("config", {}).get("event_plan") or {})
        .get("value_fault_events", 0) > 0
        or any(r.get("event") in ("sentinel_trip", "quarantine")
               for r in metrics)
    )
    if (drift is not None and wire == "f32" and not displaced
            and max(drift, wdrift or 0.0) > DRIFT_ULP_TOL):
        flags.append(
            f"push-sum mass drift up to {max(drift, wdrift or 0.0):.0f} ULPs "
            "(large for the dtype — check loss windows / dtype choice)"
        )
    return flags


def _counter_flags(manifest: Optional[Dict[str, Any]]) -> List[str]:
    """sent = delivered + dropped must hold exactly on push-sum runs with
    no churn (every attempted share either moves mass or is dropped by a
    loss window). Gossip breaks the identity by design (receiver-side
    suppression is "sent, not delivered"), dead receivers ignoring
    shares break it under kill schedules, and topology-schedule events
    (events/) legitimately change per-round sent/delivered totals when a
    mid-run edge rewrite strands in-flight accounting or the partition
    rule executes a split-off component — all gated out rather than
    special-cased, so this rule never fires on a healthy run."""
    if manifest is None:
        return []
    counters = manifest.get("counters")
    cfg = manifest.get("config", {})
    sched = cfg.get("fault_schedule", {})
    plan = cfg.get("event_plan") or {}
    has_events = (plan.get("add_events", 0) > 0
                  or plan.get("remove_events", 0) > 0
                  or plan.get("swap_events", 0) > 0
                  or plan.get("churn") is not None
                  or plan.get("value_fault_events", 0) > 0)
    quarantined = ((manifest.get("sentinel") or {})
                   .get("quarantine_events", 0) > 0)
    if (not counters
            or cfg.get("algorithm") != "push-sum"
            or sched.get("kill_events", 0) > 0
            or has_events
            or quarantined):
        return []
    sent = int(counters.get("sent", 0))
    delivered = int(counters.get("delivered", 0))
    dropped = int(counters.get("dropped", 0))
    if sent != delivered + dropped:
        return [
            f"counter imbalance: sent={sent} but delivered={delivered} + "
            f"dropped={dropped} = {delivered + dropped} "
            "(messages unaccounted for outside loss windows)"
        ]
    return []


def _shard_flags(manifest: Optional[Dict[str, Any]]) -> List[str]:
    """Per-device attribution rule: a multi-shard run whose max/mean sent
    skew exceeds :data:`SHARD_SKEW_FACTOR` has an unbalanced partition.
    Silent on single-device runs (no ``shard_balance`` block), on runs
    below :data:`SHARD_SKEW_MIN_SENT` total messages, and with
    attribution off — so healthy smokes stay ``anomalies: none``."""
    balance = (manifest or {}).get("shard_balance")
    if not balance or balance.get("num_shards", 0) < 2:
        return []
    skew = balance.get("sent_skew_max_over_mean")
    total_sent = sum(balance.get("sent") or [])
    if not _finite(skew) or total_sent < SHARD_SKEW_MIN_SENT:
        return []
    if skew > SHARD_SKEW_FACTOR:
        return [
            f"shard imbalance: max/mean sent skew {skew:.2f}x across "
            f"{balance['num_shards']} shards (> {SHARD_SKEW_FACTOR}x — "
            "one shard owns disproportionate edge work)"
        ]
    return []


def _sweep_flags(manifest: Optional[Dict[str, Any]]) -> List[str]:
    """Lane-resolved convergence rule for batched sweeps: any lane left
    unconverged is flagged with the lane tally (replacing the generic
    DID-NOT-CONVERGE text, which would hide how many lanes finished).
    Silent on non-sweep manifests and on fully-converged sweeps."""
    sweep = (manifest or {}).get("sweep")
    if not isinstance(sweep, dict):
        return []
    lanes = sweep.get("lanes")
    conv = sweep.get("converged_lanes")
    if (isinstance(lanes, int) and isinstance(conv, int) and conv < lanes):
        stuck = [lr.get("lane") for lr in sweep.get("per_lane") or []
                 if not lr.get("converged")]
        detail = (f" (lanes {', '.join(str(i) for i in stuck[:8])}"
                  + (", ..." if len(stuck) > 8 else "") + ")"
                  if stuck else "")
        return [
            f"sweep: only {conv}/{lanes} lanes converged within the "
            f"round budget{detail}"
        ]
    return []


def _sentinel_flags(manifest: Optional[Dict[str, Any]],
                    metrics: List[Dict[str, Any]]) -> List[str]:
    """Health-sentinel rule: a trip the run did NOT recover from is an
    anomaly. A trip that was contained (quarantine/rollback) on a run
    that then converged is the sentinel doing its job — the report's
    quarantine section tells that story, and the chaos-smoke CI contract
    (converged containment run => ``anomalies: none``) stays intact."""
    trips = [r for r in metrics if r.get("event") == "sentinel_trip"]
    if not trips:
        return []
    result = (manifest or {}).get("result")
    if result is not None and result.get("converged", False):
        return []
    last = trips[-1]
    return [
        f"sentinel TRIPPED at round {last.get('round', '?')} "
        f"({last.get('cause', '?')}, {last.get('nodes', '?')} node(s), "
        f"mode {last.get('mode', '?')}) and the run did not recover"
    ]


def _budget_flags(manifest: Optional[Dict[str, Any]],
                  metrics: List[Dict[str, Any]]) -> List[str]:
    flags: List[str] = []
    pred = (manifest or {}).get("prediction")
    over_recs = [r for r in metrics if r.get("event") == "over_budget"]
    if over_recs or (pred and pred.get("over_budget")):
        # the structured record carries the budget the driver actually
        # enforced (explicit --round-budget N, not the auto prediction's
        # bound) — prefer it over the prediction block's fields
        rec = over_recs[-1] if over_recs else {}
        flags.append(
            f"EXCEEDED round budget: stopped at round "
            f"{rec.get('round', (pred or {}).get('actual_rounds', '?'))} "
            f"of budget "
            f"{rec.get('budget_rounds', (pred or {}).get('budget_rounds', '?'))}"
            f" (predicted "
            f"{(pred or {}).get('predicted_rounds', rec.get('predicted_rounds', '?'))}"
            f" rounds)"
        )
    elif (pred and pred.get("confidence") == "analytic"
          and _finite(pred.get("actual_rounds"))
          and _finite(pred.get("budget_rounds"))
          and pred["actual_rounds"] > pred["budget_rounds"]):
        flags.append(
            f"round blowout: {pred['actual_rounds']} rounds > "
            f"{pred.get('budget_factor', '?')}x the analytic prediction "
            f"({pred.get('predicted_rounds', '?')} rounds)"
        )
    return flags


def _trace_flags(manifest: Optional[Dict[str, Any]],
                 trace: Optional[List[Dict[str, Any]]]) -> List[str]:
    """Residual-shape rules. Only meaningful while the run has NOT
    converged — a converged run's tail is flat at ~0 by definition, so
    both rules gate on the manifest's converged bit (absent manifest =
    crashed run = not converged, rules apply)."""
    if not trace:
        return []
    result = (manifest or {}).get("result")
    if result is not None and result.get("converged", False):
        return []
    residuals = [r["residual"] for r in trace
                 if _finite(r.get("residual"))]
    if len(residuals) < TRACE_WINDOW:
        return []
    window = residuals[-TRACE_WINDOW:]
    first, last = window[0], window[-1]
    lo, hi = min(window), max(window)
    flags: List[str] = []
    if last >= first * DIVERGE_FACTOR and last > 0:
        flags.append(
            f"residual DIVERGING: {first:.3e} -> {last:.3e} over the last "
            f"{TRACE_WINDOW} trace rows"
        )
    elif hi > 0 and (hi - lo) <= STALL_REL_SPAN * hi:
        flags.append(
            f"residual PLATEAU: stuck at {last:.3e} over the last "
            f"{TRACE_WINDOW} trace rows without converging"
        )
    return flags


def anomaly_flags(
    manifest: Optional[Dict[str, Any]],
    metrics: List[Dict[str, Any]],
    trace: Optional[List[Dict[str, Any]]] = None,
) -> List[str]:
    """Every anomaly the records prove, most fundamental first.

    ``manifest`` is the parsed ``run.json`` (None when the run died
    before writing it), ``metrics`` the chunk metric records from
    ``events.jsonl``, ``trace`` the rows
    :func:`~gossipprotocol_tpu.obs.trace.load_trace` returned (optional —
    trace rules are skipped without it).
    """
    flags = _record_flags(manifest, metrics)
    flags += _sweep_flags(manifest)
    flags += _counter_flags(manifest)
    flags += _shard_flags(manifest)
    flags += _sentinel_flags(manifest, metrics)
    flags += _budget_flags(manifest, metrics)
    flags += _trace_flags(manifest, trace)
    if manifest is None:
        flags.append("run.json missing: run likely crashed before finishing")
    return flags


# ---------------------------------------------------------------------
# daemon-level rules (serve/ journal states, not run telemetry)


def daemon_flags(states: Dict[str, Any]) -> List[str]:
    """Every daemon anomaly the journal proves, for a replayed
    ``{request_id: RequestState}`` map (``serve.journal.replay``):

    * **queue saturation** — any request refused with the supervisor's
      queue-full message means the backlog ceiling was actually hit;
    * **prediction-ratio blowout** — a finished request that ran more
      than :data:`PREDICTION_BLOWOUT_FACTOR` times the rounds its
      admission-time *analytic* prediction priced (heuristic-confidence
      predictions never fire, same gating as the run-level rule);
    * **retry storm** — :data:`RETRY_STORM_MIN` or more infra retries
      across the journal: one request's in-policy retries stay silent,
      a flapping accelerator runtime does not.

    Same contract as :func:`anomaly_flags`: no rule fires on a healthy
    queue, because CI asserts ``anomalies: none`` on clean smokes.
    """
    from gossipprotocol_tpu.obs import slo as slo_mod

    flags: List[str] = []
    sts = list(states.values())
    saturated = [st for st in sts
                 if st.phase == "refused"
                 and str(st.last.get("reason", "")).startswith("queue full")]
    if saturated:
        flags.append(MSG_QUEUE_SATURATED.format(n=len(saturated)))
    for st in sts:
        admitted = st.first("admitted")
        if admitted is None or admitted.get(
                "prediction_confidence") != "analytic":
            continue
        ratio = slo_mod.prediction_ratio(st)
        if ratio is not None and ratio > PREDICTION_BLOWOUT_FACTOR:
            final = st.first("finished") or st.first("over_budget") or {}
            flags.append(MSG_PREDICTION_BLOWOUT.format(
                rid=st.id, rounds=final.get("rounds"), ratio=ratio,
                predicted=admitted.get("predicted_rounds")))
    retries = sum(st.retries for st in sts)
    if retries >= RETRY_STORM_MIN:
        flags.append(MSG_RETRY_STORM.format(
            n=retries, m=sum(1 for st in sts if st.retries)))
    return flags
