"""On-device message counters folded through the chunk scan.

:func:`make_counter_fn` mirrors :func:`engine.driver.build_protocol`'s
dispatch exactly — one counter function per protocol/delivery branch,
each implemented next to the round it measures (``protocols/gossip.py``,
``protocols/pushsum.py``, ``protocols/diffusion.py``,
``ops/sharddelivery.py``) so the two can never drift apart silently.

The returned function has one fixed call shape for both engines::

    counter_fn(old_state, new_state, nbrs, base_key, alive_global, gids)
        -> int32[3]   # (sent, delivered, dropped) over the LOCAL rows

and is called once per round *inside* the jitted ``while_loop`` body.
Under ``shard_map`` the caller ``psum``\\ s the vector (every component is
a sum of per-row contributions, so local-then-psum is exact).

Correctness contract (the bitwise-invariance tests pin this):

* counter functions only **read** the old/new states — they re-derive the
  round's draws through the very same counter-based primitives
  (:func:`protocols.sampling.sample_neighbors` / ``drop_mask``) the round
  itself used, so no state bit and no PRNG stream is ever perturbed;
* the counters ride in a side buffer of the loop carry and never feed
  back, so the state trajectory with telemetry on is bitwise identical
  to telemetry off.

Counter semantics, uniform across protocols:

* ``sent`` — messages a live node attempted this round (including ones a
  converged/dead receiver will ignore);
* ``delivered`` — messages accepted by a receiver (gossip: hits actually
  credited, i.e. receiver-side suppression excluded; push-sum: shares
  that moved mass);
* ``dropped`` — messages lost to an active loss window (mass-conserving
  drops: the sender kept the share).

Counts are int32 (a single round's message count is bounded by the
directed edge count, itself int32-indexed); the per-round delta rows are
summed on the host as Python ints, so *cumulative* totals never overflow.
The one exception is the implicit complete graph, where a round sends
``a·(a−1)`` messages — computed in f32 and clipped to ``INT32_MAX`` (the
count saturates beyond ~46 k alive nodes; the metrics record notes carry
exact values only below that).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np

COUNTER_FIELDS = ("sent", "delivered", "dropped")
NUM_COUNTERS = len(COUNTER_FIELDS)


def make_counter_fn(
    topo,
    cfg,
    *,
    all_alive: bool,
    targets_alive: bool,
    all_sum: Optional[Callable] = None,
    interpret: bool = False,
    axis_name: Optional[str] = None,
    clock_override: Optional[tuple] = None,
) -> Callable:
    """Build the per-round counter function for this run's exact branch.

    ``all_alive`` / ``targets_alive`` must be the flag pair
    ``build_protocol`` returned (they select the same fast paths the
    round compiled with). ``all_sum`` is the cross-shard scalar reduction
    (``jnp.sum`` single-chip, a psum closure under ``shard_map``) — only
    the implicit-complete-graph branch needs it. ``interpret`` /
    ``axis_name`` parameterize the routed-delivery branches the same way
    the round cores take them.
    """
    n = topo.num_nodes
    loss_windows = cfg.schedule.static_loss_windows()
    # same spec the round compiled with: counters re-derive the round's
    # activation draws through the same fold, so counts match senders
    from gossipprotocol_tpu.engine.driver import run_clock_spec

    clock = (clock_override if clock_override is not None
             else run_clock_spec(topo, cfg))
    if all_sum is None:
        all_sum = jnp.sum

    if cfg.algorithm == "push-sum" and cfg.workload in ("sgp", "gala"):
        # SGP/GALA rounds are learner steps wrapped around a plain mixing
        # round; the message traffic is exactly the mixing round's, with
        # the delivery pytree riding inside the SGPBundle's nbrs slot —
        # count through the inner branch after unwrapping. GALA's clock
        # spec (group-level id_div) must survive the unwrap, so the inner
        # cfg keeps clock/activation_rate and only swaps the workload —
        # the spec is recomputed here from the *outer* cfg and closed over.
        import dataclasses as _dc

        inner = make_counter_fn(
            topo, _dc.replace(cfg, workload="avg", groups=1),
            all_alive=all_alive, targets_alive=targets_alive,
            all_sum=all_sum, interpret=interpret, axis_name=axis_name,
            clock_override=clock,
        )

        def fn(old, new, bundle, base_key, alive_global, gids):
            return inner(old, new, bundle.nbrs, base_key, alive_global,
                         gids)

        return fn

    if cfg.algorithm == "push-sum" and cfg.accel != "off":
        # the accelerated rounds apply the same one-W-pass diffusion
        # delivery as plain scatter diffusion; the affine recombination
        # moves no messages
        import dataclasses as _dc

        return make_counter_fn(
            topo, _dc.replace(cfg, accel="off"),
            all_alive=all_alive, targets_alive=targets_alive,
            all_sum=all_sum, interpret=interpret, axis_name=axis_name,
            clock_override=clock,
        )

    if cfg.algorithm == "gossip":
        from gossipprotocol_tpu.engine.driver import effective_keep_alive
        from gossipprotocol_tpu.protocols.gossip import gossip_message_counts

        keep_alive = effective_keep_alive(topo, cfg)

        def fn(old, new, nbrs, base_key, alive_global, gids):
            return gossip_message_counts(
                old, new, nbrs, base_key, n=n, gids=gids,
                keep_alive=keep_alive, all_alive=all_alive,
                loss_windows=loss_windows, clock=clock,
            )

        return fn

    if cfg.semantics == "reference" and cfg.fanout == "one":
        # the single-token walk: exactly one message per hop, no loss
        # (RunConfig rejects fault schedules for the walk)
        def fn(old, new, nbrs, base_key, alive_global, gids):
            return jnp.array([1, 1, 0], jnp.int32)

        return fn

    if cfg.fanout == "all":
        if cfg.delivery in ("routed", "pallas", "megakernel"):
            # pallas/megakernel deliveries answer the same .matvec/
            # .degree recount surface (the exchange transport moves
            # identical slabs, so the counts — like the trajectories —
            # cannot differ; MegakernelDelivery forwards to its inner
            # PallasDelivery)
            if axis_name is not None:
                from gossipprotocol_tpu.ops.sharddelivery import (
                    shard_routed_message_counts,
                )

                fast = all_alive or targets_alive

                def fn(old, new, nbrs, base_key, alive_global, gids):
                    return shard_routed_message_counts(
                        old, nbrs, design=cfg.routed_design,
                        axis_name=axis_name, interpret=interpret,
                        fast_alive=fast, all_alive=all_alive,
                        base_key=base_key, clock=clock,
                    )

                return fn

            from gossipprotocol_tpu.protocols.diffusion import (
                routed_message_counts,
            )

            def fn(old, new, nbrs, base_key, alive_global, gids):
                return routed_message_counts(
                    old, nbrs, n=n, all_alive=all_alive,
                    targets_alive=targets_alive, interpret=interpret,
                    base_key=base_key, clock=clock,
                )

            return fn

        from gossipprotocol_tpu.protocols.diffusion import (
            diffusion_message_counts,
        )

        def fn(old, new, nbrs, base_key, alive_global, gids):
            return diffusion_message_counts(
                old, nbrs, base_key, n=n, gids=gids, all_alive=all_alive,
                targets_alive=targets_alive, loss_windows=loss_windows,
                alive_global=alive_global, all_sum=all_sum, clock=clock,
            )

        return fn

    from gossipprotocol_tpu.protocols.pushsum import pushsum_message_counts

    def fn(old, new, nbrs, base_key, alive_global, gids):
        return pushsum_message_counts(
            old, nbrs, base_key, n=n, gids=gids, all_alive=all_alive,
            targets_alive=targets_alive, delivery=cfg.delivery,
            loss_windows=loss_windows, alive_global=alive_global,
            clock=clock,
        )

    return fn


def ulp_drift(value, baseline) -> float:
    """|value − baseline| measured in ULPs *of the baseline's dtype*.

    Both values come straight off the device (numpy scalars in the run
    dtype), so ``np.spacing`` yields the correct unit in f32 and f64
    runs alike. Exact-conservation runs (dyadic push-sum arithmetic)
    report exactly 0.0; any rounding or genuine mass change is >= 1.

    Vector payloads pass per-dimension [d] mass sums: drift is then
    measured per dimension against that dimension's own baseline and the
    *max* over dimensions is reported — one bad column must not be
    averaged away by d−1 exact ones.
    """
    b = np.asarray(baseline)
    v = np.asarray(value)
    if b.ndim:
        return max(
            ulp_drift(v.reshape(-1)[k], b.reshape(-1)[k])
            for k in range(b.size)
        )
    v = float(np.float64(v))
    bf = float(np.float64(b))
    if v == bf:
        return 0.0
    ulp = float(np.spacing(np.abs(b).astype(b.dtype, copy=False)))
    if ulp == 0.0:  # baseline exactly 0 in a zero-width format corner
        ulp = float(np.spacing(np.asarray(0, b.dtype)))
    return abs(v - bf) / ulp
