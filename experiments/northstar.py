"""North-star config end-to-end (VERDICT #8; BASELINE.md:36-37).

One artifact exercising the whole aux stack under load, in four acts:

  1. build a 10M-node Erdős–Rényi graph (native C++ builder),
  2. run push-sum with fault injection + per-chunk JSONL metrics +
     periodic checkpoints, deliberately interrupted by a round budget,
  3. resume from the latest checkpoint to convergence, and verify the
     resumed trajectory equals an uninterrupted control run bitwise,
  4. re-run the same config shape sharded over an 8-device CPU mesh
     (reduced scale — the multi-chip semantics check without hardware),
  5. run the power-law variant at full scale (BASELINE.md:36-37 names
     both graphs; power-law exceeds DENSE_MAX_DEGREE, so this also
     exercises the CSR sampling path at 10M) — first the reference's
     single-target send (bounded: provably O(max_degree) rounds on a hub
     graph), then fanout-all diffusion (``--fanout all``), which
     converges at mixing time and certifies the mean to tol.

Writes ``artifacts/northstar_pushsum_er.jsonl`` (per-chunk records for
the full interrupted+resumed run) and
``artifacts/northstar_summary.json``.

    python experiments/northstar.py            # full 10M (TPU, ~2 min)
    NORTHSTAR_NODES=100000 python experiments/northstar.py   # smoke
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts")


def main():
    import numpy as np

    import jax

    from gossipprotocol_tpu import RunConfig, build_topology, run_simulation
    from gossipprotocol_tpu.engine import resume_simulation
    from gossipprotocol_tpu.utils import checkpoint as ckpt
    from gossipprotocol_tpu.utils import faults
    from gossipprotocol_tpu.utils.metrics import JsonlMetricsWriter

    n = int(os.environ.get("NORTHSTAR_NODES", 10_000_000))
    ckdir = os.path.join(ART, "northstar_ck")
    os.makedirs(ART, exist_ok=True)
    # checkpoints from a previous (e.g. smoke-scale) invocation must not
    # be resumable into this run
    import shutil

    shutil.rmtree(ckdir, ignore_errors=True)

    # --- act 1: topology ------------------------------------------------
    print(f"[northstar] act 1: building ER n={n} ...", flush=True)
    t0 = time.perf_counter()
    topo = build_topology("erdos_renyi", n, avg_degree=8.0, seed=0)
    build_s = time.perf_counter() - t0

    # 1% of nodes die at round 60 (SURVEY.md §5.3: gossip/push-sum
    # robustness under failure is the algorithm family's whole point)
    plan = faults.random_fault_plan(topo.num_nodes, 0.01, 60, seed=0)

    jsonl_path = os.path.join(ART, "northstar_pushsum_er.jsonl")
    writer = JsonlMetricsWriter(jsonl_path, mode="w")
    # predicate="global": the sound rule (|s/w - alive-mean| <= tol). The
    # reference's intended delta rule is demonstrably meaningless at this
    # scale — float32 ratio increments vanish below eps long before mixing,
    # so it "converges" at 10M with error ~0.49 (documented unsoundness,
    # README + curves artifact); the north-star artifact should certify a
    # *correct* answer, which only the global rule can.
    base = RunConfig(
        algorithm="push-sum", seed=0, chunk_rounds=64,
        predicate="global", tol=1e-4,
        fault_plan=plan, metrics_callback=writer,
        checkpoint_every=2, checkpoint_dir=ckdir,
    )

    # --- act 2: control run (also the probe for the interruption point) --
    print("[northstar] act 2: control run ...", flush=True)
    control = run_simulation(topo, dataclasses.replace(
        base, metrics_callback=None, checkpoint_every=0, checkpoint_dir=None,
    ))
    assert control.converged

    # --- act 3: interrupted run + resume, verified against the control ---
    # stop mid-flight at half the known round count, with a chunk size that
    # guarantees at least one checkpoint lands before the budget
    print(f"[northstar] control: rounds={control.rounds} wall={control.wall_ms/1e3:.1f}s", flush=True)
    print("[northstar] act 3: interrupted + resume ...", flush=True)
    budget = max(control.rounds // 2, 8)
    res1 = run_simulation(topo, dataclasses.replace(
        base, max_rounds=budget,
        chunk_rounds=max(budget // 2, 4), checkpoint_every=1,
    ))
    assert not res1.converged and res1.checkpoints, "should stop at budget"

    latest = ckpt.latest(ckdir)
    state, meta = ckpt.load(latest)
    assert meta["algorithm"] == "push-sum" and meta["round"] <= budget
    res2 = resume_simulation(topo, base, state)
    writer.close()

    s_match = bool(np.array_equal(
        np.asarray(res2.final_state.s), np.asarray(control.final_state.s)
    ))
    rounds_match = res2.rounds == control.rounds

    # --- act 4: same config shape on the 8-device virtual mesh -----------
    print("[northstar] act 4: sharded cpu8 ...", flush=True)
    shard_n = min(n, 65536)
    proc = subprocess.run(
        [sys.executable, "-m", "gossipprotocol_tpu", str(shard_n),
         "erdos_renyi", "push-sum", "--devices", "8", "--backend", "cpu",
         "--seed", "0", "--chunk-rounds", "64",
         "--predicate", "global", "--tol", "1e-4"],
        capture_output=True, text=True, timeout=1200, cwd=REPO,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    )
    shard_ok = proc.returncode == 0 and "devices: 8" in proc.stdout

    # --- act 5: power-law at full scale (CSR sampling path) ---------------
    # Bounded, not run to the global tol: a leaf hanging off a degree-10k
    # hub is picked by the hub with p ~ 1e-4 per round, so its estimate
    # needs O(max_degree) rounds' worth of receipts to reach tol — an
    # intrinsic property of uniform-neighbor push-sum on hub graphs, not
    # an engine limit. The act therefore demonstrates the 10M power-law
    # *scale* capability (BASELINE.md:36-37) and reports how far the error
    # dropped in the budget, plus exact mass conservation.
    print("[northstar] act 5: power-law full scale ...", flush=True)
    t0 = time.perf_counter()
    topo_pl = build_topology("power_law", n, m=4, seed=0)
    pl_build_s = time.perf_counter() - t0
    # chunk_rounds stays modest: one 10M-row chunk of ~250 rounds is a
    # >2-minute single device program, which trips the remote-execution
    # watchdog (observed: TPU worker crash)
    res_pl = run_simulation(topo_pl, RunConfig(
        algorithm="push-sum", seed=0, predicate="global", tol=1e-4,
        chunk_rounds=64, max_rounds=1_000,
    ))
    pl_state = res_pl.final_state
    pl_mass = float(np.asarray(pl_state.w, np.float64).sum())
    # float32 mass drift is REAL on hub graphs (SURVEY.md §7 hard part d):
    # once the mega-hub's w reaches ~2^23, each incoming half-weight is at
    # ulp scale and the scatter-add leaks — measured ~0.7% over 1k rounds.
    # Quantified here; act 5b shows float64 removes it.
    pl_drift = abs(pl_mass - topo_pl.num_nodes) / topo_pl.num_nodes

    # --- act 5c: power-law to ACTUAL convergence via fanout-all diffusion -
    # The single-target send above is the reference's accidental behavior
    # (Program.fs:128); the claimed capability is averaging. Diffusion
    # (--fanout all: every node ships a 1/(deg+1) share to every neighbor,
    # delivery = one segment_sum over the 80M-edge list) converges at
    # graph mixing time, so THIS config certifies the mean at 10M
    # power-law — closing the one BASELINE row the single-target variant
    # provably cannot (VERDICT r2 missing #1).
    print("[northstar] act 5c: power-law fanout-all diffusion ...", flush=True)
    # chunk_rounds=8: a diffusion round walks all ~80M edges (two streaming
    # gathers + two random scatters), measured ~5.2 s/round at this scale —
    # 32-round chunks are ~170 s single dispatches, which the remote
    # watchdog kills (observed: TPU worker crash mid-act)
    res_pld = run_simulation(topo_pl, RunConfig(
        algorithm="push-sum", seed=0, predicate="global", tol=1e-4,
        fanout="all", chunk_rounds=8, max_rounds=2_000,
    ))
    pld_s = np.asarray(res_pld.final_state.s, np.float64)
    pld_w = np.asarray(res_pld.final_state.w, np.float64)
    pld_mass = float(pld_w.sum())
    pld_drift = abs(pld_mass - topo_pl.num_nodes) / topo_pl.num_nodes
    # f32 numerics at the hub, measured: the degree-1M hub's per-round
    # in-sum is a ~1M-term serial f32 accumulation, leaking ~0.03%/round
    # of TOTAL mass (2.2% over the 71-round run). s and w leak
    # near-proportionally (the two streams are ~proportional elementwise
    # at convergence), so the certified target Σs/Σw moves 240x less
    # than the mass: measured ratio drift 9.3e-5 ≈ tol — estimates are
    # within ~1.3 tol of the TRUE initial mean (both asserted below).
    # f32 at this scale certifies the mean to tol-scale, not beyond;
    # --x64 is the tighter option (act 5b shows it conserves exactly).
    pld_mean_init = (topo_pl.num_nodes - 1) / (2.0 * topo_pl.num_nodes)
    pld_ratio_drift = abs(float(pld_s.sum() / pld_w.sum()) - pld_mean_init)
    pld_err_vs_init = float(np.abs(
        pld_s / np.maximum(pld_w, 1e-30) - pld_mean_init
    )[np.asarray(res_pld.final_state.alive)].max())

    print("[northstar] act 5b: power-law float64 numerics ...", flush=True)
    import jax.numpy as jnp

    jax.config.update("jax_enable_x64", True)  # last act; nothing f32 follows
    # tiny chunks: TPU f64 is software-emulated (~10-30x slower), and a
    # multi-minute on-device chunk trips the remote watchdog (observed)
    res_pl64 = run_simulation(topo_pl, RunConfig(
        algorithm="push-sum", seed=0, predicate="global", tol=1e-4,
        chunk_rounds=4, max_rounds=16, dtype=jnp.float64,
    ))
    pl64_mass = float(np.asarray(res_pl64.final_state.w, np.float64).sum())
    pl64_drift = abs(pl64_mass - topo_pl.num_nodes) / topo_pl.num_nodes

    summary = {
        "config": {
            "nodes": topo.num_nodes, "topology": "erdos_renyi",
            "avg_degree": 8.0, "algorithm": "push-sum", "seed": 0,
            "predicate": "global", "tol": 1e-4,
            "fault": "1% of nodes at round 60",
        },
        "topology_build_s": round(build_s, 2),
        "interrupted_at_round": res1.rounds,
        "checkpoints_written": len(res1.checkpoints),
        "resumed_rounds_total": res2.rounds,
        "resumed_converged": res2.converged,
        "resumed_wall_s": round((res1.wall_ms + res2.wall_ms) / 1e3, 2),
        "estimate_error_vs_alive_mean": control.estimate_error,
        "resume_bitwise_equals_uninterrupted": s_match and rounds_match,
        "control_wall_s": round(control.wall_ms / 1e3, 2),
        "alive_final": int(np.asarray(control.final_state.alive).sum()),
        "sharded_cpu8_reduced_scale": {
            "nodes": shard_n, "ok": shard_ok,
            "stdout_tail": proc.stdout.strip().splitlines()[-2:],
        },
        "power_law_full_scale": {
            "nodes": topo_pl.num_nodes, "m": 4,
            "max_degree": int(topo_pl.max_degree),
            "build_s": round(pl_build_s, 2),
            "rounds": res_pl.rounds,
            "converged": res_pl.converged,
            "wall_s": round(res_pl.wall_ms / 1e3, 2),
            "estimate_error": res_pl.estimate_error,
            "sum_w_final_f32": pl_mass,
            "mass_drift_f32": pl_drift,
            "mass_drift_f64_16rounds": pl64_drift,
            "note": "bounded run: hub-leaf receipt rate makes global-tol "
                    "convergence O(max_degree) rounds — capability demo, "
                    "error-at-budget reported. f32 scatter-add into the "
                    "degree-1M hub leaks w at ulp scale (quantified); "
                    "--x64 eliminates it (also quantified). The fanout-all "
                    "diffusion entry below is the variant that actually "
                    "certifies the mean on this graph",
            "diffusion_fanout_all": {
                "rounds": res_pld.rounds,
                "converged": res_pld.converged,
                "wall_s": round(res_pld.wall_ms / 1e3, 2),
                "estimate_error": res_pld.estimate_error,
                "mass_drift_f32": pld_drift,
                "ratio_drift_vs_init_mean": pld_ratio_drift,
                "estimate_error_vs_init_mean": pld_err_vs_init,
                "note": (
                    "f32 segment-sum into the degree-1M hub accumulates "
                    "serial-rounding drift in TOTAL mass (~0.03%/round), "
                    "but s and w leak near-proportionally: the certified "
                    "target Σs/Σw moves 240x less than the mass (9.3e-5, "
                    "= tol scale), and every node ends within ~1.3 tol "
                    "of the TRUE initial mean (fields above). f32 "
                    "certifies the mean to tol-scale at this hub size; "
                    "--x64 conserves exactly (act 5b)"
                ),
            },
        },
        "backend": jax.default_backend(),
    }
    out = os.path.join(ART, "northstar_summary.json")
    with open(out, "w") as fh:
        json.dump(summary, fh, indent=2)
    print(json.dumps(summary, indent=2))
    assert s_match and rounds_match, "resume transparency violated"
    assert res2.converged and shard_ok
    # power-law numerics: f32 hub leakage stays within its measured band;
    # f64 conserves mass to float64 rounding (SURVEY.md §7 hard part d)
    assert pl_drift < 0.02, f"f32 hub drift grew: {pl_drift}"
    assert pl64_drift < 1e-9, f"f64 should conserve mass: {pl64_drift}"
    # the north-star closure: power-law 10M actually certifies the mean
    assert res_pld.converged, "diffusion power-law must converge"
    assert res_pld.estimate_error <= 1.01e-4, res_pld.estimate_error
    # f32 hub accumulation leaks TOTAL mass within its measured band
    # (2.2% at 71 rounds; see the note above) — but the certificate's
    # target ratio must not drift, and estimates must be within tol of
    # the TRUE initial mean, not merely the drifted one
    assert pld_drift < 0.05, f"diffusion f32 mass drift grew: {pld_drift}"
    assert pld_ratio_drift < 2e-4, f"certified mean drifted: {pld_ratio_drift}"
    assert pld_err_vs_init <= 2e-4, f"error vs true mean: {pld_err_vs_init}"


if __name__ == "__main__":
    main()
