"""Cross the 100M push-sum memory wall (VERDICT r3 #3).

Round 3 mapped two walls at 100M nodes: f32 single-target cannot certify
(w underflows in receipt dry spells), and fanout-all diffusion — the
variant that certifies — needed 18.07 GB of per-edge intermediates vs
15.75 GB of HBM. The cure shipped this round is ``--edge-chunks K``:
delivery in K sequential edge slices, K-fold smaller intermediates.
This script runs 100M-node Erdős–Rényi fanout-all diffusion, f32,
edge-chunked, under the sound global predicate, recording per-chunk
error so the artifact shows the wall CROSSED (the config compiles,
fits, executes, and the certified error descends) and — budget
permitting — certified.

Usage: python experiments/pushsum_100m.py [--max-rounds 16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000_000)
    ap.add_argument("--avg-degree", type=float, default=8.0)
    ap.add_argument("--edge-chunks", type=int, default=6)
    ap.add_argument("--max-rounds", type=int, default=16)
    ap.add_argument("--tol", type=float, default=1e-4)
    ap.add_argument("--out", default="artifacts/pushsum_100M_diffusion.json")
    args = ap.parse_args()

    from gossipprotocol_tpu import RunConfig, build_topology, run_simulation

    t0 = time.perf_counter()
    topo = build_topology("erdos_renyi", args.nodes, seed=0,
                          avg_degree=args.avg_degree)
    build_s = time.perf_counter() - t0
    print(f"topology: {topo.num_nodes} nodes, "
          f"{topo.num_directed_edges} directed edges ({build_s:.0f}s)",
          flush=True)

    jsonl = os.path.join(REPO, "artifacts", "pushsum_100M_diffusion.jsonl")
    with open(jsonl, "w") as fh:
        def cb(rec):
            fh.write(json.dumps(rec) + "\n")
            fh.flush()
            print(rec, flush=True)

        cfg = RunConfig(
            algorithm="push-sum", fanout="all", predicate="global",
            tol=args.tol, seed=0, edge_chunks=args.edge_chunks,
            chunk_rounds=1, max_rounds=args.max_rounds,
            metrics_callback=cb,
        )
        res = run_simulation(topo, cfg)

    rec = {
        "config": {
            "nodes": topo.num_nodes,
            "topology": f"erdos_renyi(avg_degree={args.avg_degree})",
            "directed_edges": topo.num_directed_edges,
            "algorithm": "push-sum fanout-all diffusion",
            "dtype": "float32",
            "predicate": f"global tol={args.tol}",
            "edge_chunks": args.edge_chunks,
            "round_budget": args.max_rounds,
        },
        "rounds": int(res.rounds),
        "converged": bool(res.converged),
        "estimate_error_final": float(res.estimate_error)
        if res.estimate_error is not None else None,
        "wall_ms": round(res.wall_ms, 1),
        "ms_per_round": round(res.wall_ms / max(res.rounds, 1), 1),
        "compile_ms": round(res.compile_ms, 1),
        "topology_build_s": round(build_s, 1),
        "backend": "tpu (v5e single chip)",
        "notes": [
            "VERDICT r3 #3: round 3 measured this config's per-edge "
            "intermediates at 18.07 GB vs 15.75 GB HBM — it could not "
            "compile. --edge-chunks slices the delivery; this run "
            "compiles, fits, and executes at 100M on one chip.",
            "per-round records (converged counts, error trajectory) in "
            "pushsum_100M_diffusion.jsonl",
        ],
    }
    with open(os.path.join(REPO, args.out), "w") as fh:
        json.dump(rec, fh, indent=1)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
