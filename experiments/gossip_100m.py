"""100M-node gossip re-record with the inverted delivery ON (VERDICT r3 #2).

Round 3's 100M run had to disable the engine's own gather-inverted
delivery: the ~3 GB inversion tables uploaded in a single device_put
transaction and the remote worker's watchdog killed it. Uploads now go
through ``chunked_put`` (<= 512 MB slices), so this run compiles the
full engine — scatter + inversion with the per-round on-device switch —
and should sit near the engine's ~3.6x-faster saturated-phase delivery.

Writes artifacts/gossip_100M.json (+ per-chunk JSONL) over round 3's
all-scatter record.

Usage: python experiments/gossip_100m.py [--nodes 100000000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000_000)
    ap.add_argument("--out", default="artifacts/gossip_100M.json")
    args = ap.parse_args()

    import jax

    from gossipprotocol_tpu import RunConfig, build_topology, run_simulation

    records = []
    t0 = time.perf_counter()
    topo = build_topology("imp3D", args.nodes, seed=0)
    build_s = time.perf_counter() - t0
    print(f"topology: {topo.num_nodes} nodes ({build_s:.0f}s)", flush=True)

    jsonl = os.path.join(REPO, "artifacts", "gossip_100M.jsonl")
    with open(jsonl, "w") as fh:
        def cb(rec):
            fh.write(json.dumps(rec) + "\n")
            fh.flush()
            print(rec, flush=True)

        cfg = RunConfig(algorithm="gossip", seed=0, chunk_rounds=24,
                        max_rounds=4096, metrics_callback=cb)
        res = run_simulation(topo, cfg)

    rec = {
        "config": {
            "nodes_requested": args.nodes,
            "nodes_actual": topo.num_nodes,
            "topology": "imp3D",
            "algorithm": "gossip",
            "seed": 0,
            "chunk_rounds": 24,
            "delivery": "engine default (scatter + gather-inversion, "
                        "on-device per-round switch)",
        },
        "rounds": int(res.rounds),
        "converged": bool(res.converged),
        "wall_ms": round(res.wall_ms, 1),
        "ms_per_round": round(res.wall_ms / max(res.rounds, 1), 1),
        "compile_ms": round(res.compile_ms, 1),
        "topology_build_s": round(build_s, 1),
        "backend": "tpu (v5e single chip)",
        "notes": [
            "10,000x the reference's demonstrated 9k-node ceiling, on "
            "ONE chip",
            "re-recorded with the inverted delivery ENABLED: the round-3 "
            "blocker (one ~3 GB device_put of the inversion tables "
            "tripping the remote watchdog) is gone — chunked_put splits "
            "every upload into <=512 MB transactions",
            "round-3 all-scatter baseline: 77 rounds / 94.3 s "
            "(~1.2 s/round)",
            "per-chunk records in gossip_100M.jsonl",
        ],
    }
    with open(os.path.join(REPO, args.out), "w") as fh:
        json.dump(rec, fh, indent=1)
    print(json.dumps(rec), flush=True)
    assert res.converged


if __name__ == "__main__":
    main()
