"""Bisect the routed round's 13.6 GB temp blowup (one build, many compiles).

Compiles subprograms of the routed diffusion round at --nodes scale and
prints each one's XLA temp size: each plan chain alone, the expand, the
reduce, the full matvec, one bare round, and the 4-round chunk loop.

Usage: python experiments/routed_mem_bisect.py [--nodes 2000000]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from gossipprotocol_tpu import build_topology
from gossipprotocol_tpu.engine.driver import (
    RunConfig, build_protocol, device_arrays, make_chunk_runner,
)
from gossipprotocol_tpu.ops.exec import apply_plan


def report(name, lowered):
    c = lowered.compile()
    ma = c.memory_analysis()
    print(f"{name:28s} args {ma.argument_size_in_bytes/1e9:6.2f} GB  "
          f"temps {ma.temp_size_in_bytes/1e9:6.2f} GB", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2_000_000)
    args = ap.parse_args()
    topo = build_topology("powerlaw", args.nodes, seed=7, m=4)
    cfg = RunConfig(algorithm="push-sum", fanout="all", predicate="global",
                    tol=1e-4, seed=11, delivery="routed")
    t0 = time.perf_counter()
    rd = device_arrays(topo, cfg)
    print(f"plan build: {time.perf_counter()-t0:.0f}s", flush=True)
    n = topo.num_nodes

    x = jnp.zeros(rd.plan_m[0].m_in_f32, jnp.float32)

    def chain(plans, x):
        for p in plans:
            pad = p.m_in_f32 - x.shape[0]
            x = apply_plan(p, jnp.pad(x, (0, pad)) if pad else x)
        return x

    # plans must be jit ARGUMENTS (registered pytrees): closing over them
    # embeds GBs of tables as constants and stalls tracing (measured)
    report("plan_m[0] alone",
           jax.jit(lambda p, v: apply_plan(p, v)).lower(rd.plan_m[0], x))
    report("plan_m chain (2)",
           jax.jit(lambda ps, v: chain(ps, v)).lower(rd.plan_m, x))
    xn = jnp.zeros(rd.plan_in[0].m_in_f32, jnp.float32)
    report("plan_in chain (2)",
           jax.jit(lambda ps, v: chain(ps, v)).lower(rd.plan_in, xn))

    xs = jnp.zeros(n, jnp.float32)

    def expand_now(r, cls):
        from gossipprotocol_tpu.ops import classops as co
        segs = []
        off = 0
        for c, n_c, start, reg_rows, cap in r.classes:
            node_pairs = jax.lax.dynamic_slice_in_dim(cls, 2 * off, 2 * n_c)
            node_pairs = jnp.pad(node_pairs, (0, 2 * (cap - n_c)))
            if 2 * c <= 128:
                segs.append(co.class_expand_small(node_pairs, c))
            else:
                segs.append(co.class_expand_big(node_pairs, c))
            off += n_c
        return jnp.concatenate(segs) * r.realmask

    def reduce_now(r, f):
        from gossipprotocol_tpu.ops import classops as co
        ys = []
        for c, n_c, start, reg_rows, cap in r.classes:
            region = jax.lax.dynamic_slice_in_dim(f, 2 * start,
                                                  reg_rows * 128)
            if 2 * c <= 128:
                packed = co.class_reduce_small(region, c)
            else:
                packed = co.class_reduce_big(region, c)
            ys.append(packed[: 2 * n_c])
        return jnp.concatenate(ys)

    clsv = jnp.zeros(rd.nu * 2, jnp.float32)
    report("expand only", jax.jit(expand_now).lower(rd, clsv))
    fin = jnp.zeros(rd.m_pairs * 2, jnp.float32)
    report("reduce only", jax.jit(reduce_now).lower(rd, fin))
    report("full matvec",
           jax.jit(lambda r, a, b: r.matvec(a, b)).lower(rd, xs, xs))

    state, core, done, extra, _fl = build_protocol(topo, cfg)
    report("one round",
           jax.jit(lambda s, r: core(s, r, jax.random.PRNGKey(0))).lower(
               state, rd))
    runner = make_chunk_runner(core, done, extra)
    report("chunk loop (limit arg)",
           runner.lower(state, rd, jax.random.PRNGKey(0), jnp.int32(4)))


if __name__ == "__main__":
    main()
