"""Microkernel probe: arbitrary [128,128]-tile permutation on the TPU.

route_probe2.py established that every per-element index op XLA offers
costs ~7 ns/element while vectorized ops run at stream speed.  The routed
delivery plan therefore needs ONE in-VMEM primitive: apply an arbitrary
static permutation to a [128, 128] tile using only supported Mosaic ops.

Theory (3-pass matrix routing / König): any permutation of an R x C
matrix factors as (permute within rows) o (permute within columns) o
(permute within rows).  A within-column permutation is T o rowperm o T.
So:  perm = L3 o T o L2 o T o L1  with L* = per-row lane gathers
(tpu.dynamic_gather dim 1 — measured fast) and T = [128,128] transpose.
The routing (which lane each element takes through the middle stage) is
a proper 128-edge-coloring of the bipartite src-row x dst-row multigraph,
computed here by repeated greedy/augmenting matchings (host, numpy).

This probe: build a random 16K permutation, route it, run the kernel on
the chip, check exactness vs jnp.take, and time it amortized.

Usage: python experiments/tile_perm_probe.py [--tiles 488] [--interpret]
"""

from __future__ import annotations

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp


# --------------------------------------------------------------------------
# host-side routing: 3-stage Clos decomposition of a tile permutation
# --------------------------------------------------------------------------

def edge_color_bipartite(src_rows: np.ndarray, dst_rows: np.ndarray,
                         n: int = 128) -> np.ndarray:
    """Proper n-edge-coloring of an n-regular bipartite multigraph.

    Edges e: src_rows[e] -> dst_rows[e]; every left and right node has
    degree exactly n (a permutation of an [n, n] tile guarantees this).
    Returns color[e] in [0, n).  Algorithm: peel one perfect matching per
    color via Hopcroft-Karp-ish augmenting paths on the remaining
    multigraph.  O(n^2) edges, n colors — fine for a probe; the real
    plan compiler vectorizes or goes native.
    """
    E = len(src_rows)
    assert E == n * n
    color = np.full(E, -1, np.int32)
    # adjacency: for each left node, list of (edge_id, right)
    adj = [[] for _ in range(n)]
    for e in range(E):
        adj[src_rows[e]].append(e)
    remaining = [list(lst) for lst in adj]
    for c in range(n):
        # find a perfect matching in the remaining multigraph
        match_r = np.full(n, -1, np.int32)   # right -> edge id
        match_l = np.full(n, -1, np.int32)   # left -> edge id

        def try_assign(left, seen):
            for e in remaining[left]:
                if color[e] != -1:
                    continue
                r = dst_rows[e]
                if seen[r]:
                    continue
                seen[r] = True
                if match_r[r] == -1 or try_assign(src_rows[match_r[r]], seen):
                    match_r[r] = e
                    match_l[left] = e
                    return True
            return False

        for left in range(n):
            if match_l[left] == -1:
                seen = np.zeros(n, bool)
                if not try_assign(left, seen):
                    raise RuntimeError("no perfect matching (not regular?)")
        for left in range(n):
            e = match_l[left]
            color[e] = c
            remaining[left].remove(e)
    return color


def route_tile_perm(perm: np.ndarray, n: int = 128):
    """Decompose `out.flat[k] = in.flat[perm[k]]` on an [n, n] tile.

    Returns (idx1, idx2, idx3) int32 [n, n] lane-gather index arrays:
        A = take_along_axis(X,   idx1, axis=1)   # place into color lane
        B = take_along_axis(A.T, idx2, axis=1)   # within-column route
        Y = take_along_axis(B.T, idx3, axis=1)   # final lane placement
    """
    perm = np.asarray(perm, np.int64)
    k = np.arange(n * n, dtype=np.int64)
    src = perm
    src_row, src_col = src // n, src % n
    dst_row, dst_col = k // n, k % n
    color = edge_color_bipartite(src_row, dst_row, n)

    idx1 = np.zeros((n, n), np.int32)   # A[r, c] = X[r, idx1[r, c]]
    idx2 = np.zeros((n, n), np.int32)   # B[c, r] = A[idx2[c, r], c] (as A.T rows)
    idx3 = np.zeros((n, n), np.int32)   # Y[r, c] = B.T[r, idx3[r, c]]
    # stage 1: element e sits at (src_row, src_col); goes to lane color[e]
    idx1[src_row, color] = src_col
    # stage 2: operate on A.T (shape [n cols, n rows]): row c of A.T holds
    # column c of A; element e is at (color, src_row) there and must move
    # to (color, dst_row)
    idx2[color, dst_row] = src_row
    # stage 3: operate on B.T (shape [n rows, n cols]): element e is at
    # (dst_row, color) and must land at (dst_row, dst_col)
    idx3[dst_row, dst_col] = color
    return idx1, idx2, idx3


def apply_route_np(x, idx1, idx2, idx3):
    a = np.take_along_axis(x, idx1, axis=1)
    b = np.take_along_axis(a.T, idx2, axis=1)
    y = np.take_along_axis(b.T, idx3, axis=1)
    return y


# --------------------------------------------------------------------------
# kernel
# --------------------------------------------------------------------------

def make_kernel(T: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(x_ref, i1_ref, i2_ref, i3_ref, o_ref):
        x = x_ref[0]
        a = jnp.take_along_axis(x, i1_ref[0].astype(jnp.int32), axis=1)
        b = jnp.take_along_axis(a.T, i2_ref[0].astype(jnp.int32), axis=1)
        o_ref[0] = jnp.take_along_axis(b.T, i3_ref[0].astype(jnp.int32),
                                       axis=1)

    spec_f = pl.BlockSpec((1, 128, 128), lambda i: (i, 0, 0))
    return pl.pallas_call(
        kern,
        grid=(T,),
        out_shape=jax.ShapeDtypeStruct((T, 128, 128), jnp.float32),
        in_specs=[spec_f, spec_f, spec_f, spec_f],
        out_specs=spec_f,
        interpret=interpret,
    )


def sync(x):
    return float(jax.device_get(jnp.sum(x.ravel()[:8].astype(jnp.float32))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tiles", type=int, default=488)  # ~8M elements
    ap.add_argument("--interpret", action="store_true")
    args = ap.parse_args()
    rng = np.random.default_rng(0)
    print(f"device: {jax.devices()[0]}", flush=True)

    # one routed random permutation, checked on host
    perm = rng.permutation(128 * 128)
    t0 = time.perf_counter()
    idx1, idx2, idx3 = route_tile_perm(perm)
    t_route = time.perf_counter() - t0
    x_np = rng.standard_normal((128, 128)).astype(np.float32)
    y_np = apply_route_np(x_np, idx1, idx2, idx3)
    ref = x_np.reshape(-1)[perm].reshape(128, 128)
    assert np.array_equal(y_np, ref), "host routing is WRONG"
    print(f"host routing: exact ({t_route*1e3:.0f} ms to route one tile)",
          flush=True)

    # tile it up for the device (same perm every tile is fine for timing;
    # int8 index streams, converted in-kernel)
    T = args.tiles
    x = jnp.asarray(
        rng.standard_normal((T, 128, 128)), jnp.float32)
    mk = lambda a: jnp.asarray(
        np.broadcast_to(a.astype(np.int8), (T, 128, 128)))
    i1, i2, i3 = mk(idx1), mk(idx2), mk(idx3)

    call = make_kernel(T, args.interpret)

    @jax.jit
    def run(x):
        return call(x, i1, i2, i3)

    y = jax.device_get(run(x))
    ref = np.asarray(jax.device_get(x)).reshape(T, -1)[:, perm].reshape(
        T, 128, 128)
    assert np.array_equal(y, ref), "kernel output is WRONG"
    print("kernel: exact on all tiles", flush=True)

    if args.interpret:
        return

    R = 32

    @jax.jit
    def loop(x):
        def body(i, x):
            y = call(x, i1, i2, i3)
            return y
        return jax.lax.fori_loop(0, R, body, x)

    def timed(fn, repeats=3):
        fn()
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t = timed(lambda: sync(loop(x))) / R
    nelem = T * 128 * 128
    nbytes = nelem * (4 + 4 + 3)  # data r/w f32 + 3 int8 idx streams
    print(f"tile-perm kernel: {t*1e3:9.3f} ms for {nelem/1e6:.1f}M elems  "
          f"{t/nelem*1e9:6.3f} ns/elem  {nbytes/t/1e9:6.1f} GB/s",
          flush=True)


if __name__ == "__main__":
    main()
