"""Assemble the certified 100M push-sum artifact (VERDICT r4 #1).

The run was driven by the CLI (checkpoints every 10 rounds,
--auto-resume armed); its final 1.8 GB state fetch hung on a stalled
tunnel RPC after certification (the dead-client failure mode the
elastic-recovery design exists for), so this script distills the
on-disk evidence instead: the per-round device records
(pushsum_100M_converged.jsonl — the predicate is evaluated ON DEVICE),
a host-side recomputation from the round-120 checkpoint
cross-validating that predicate, and wall-clock from the record
timeline.

Usage: python experiments/pushsum_100m_artifact.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # for the checkpoint loader import below


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--jsonl",
                    default="artifacts/pushsum_100M_converged.jsonl")
    ap.add_argument("--ckpt",
                    default="artifacts/pushsum100m_ck/"
                            "ckpt_round000000120.npz")
    ap.add_argument("--out", default="artifacts/pushsum_100M_diffusion.json")
    ap.add_argument("--tol", type=float, default=1e-4)
    args = ap.parse_args()

    recs = [json.loads(line)
            for line in open(os.path.join(REPO, args.jsonl))]
    last = recs[-1]
    converged = last["converged"] == last["alive"]
    rounds = int(last["round"])

    # independent host check of the on-device predicate, from the last
    # checkpoint before certification
    from gossipprotocol_tpu.utils import checkpoint as ckpt_mod

    state, meta = ckpt_mod.load(os.path.join(REPO, args.ckpt))
    s = np.asarray(state.s, np.float64)
    w = np.asarray(state.w, np.float64)
    alive = np.asarray(state.alive)
    mean = s[alive].sum() / w[alive].sum()
    err = np.abs(np.asarray(state.ratio, np.float64)[alive] - mean)
    ck_round = int(meta["round"])
    ck_outside = int((err > args.tol).sum())
    ck_jsonl = next(r for r in recs if r["round"] == ck_round)
    cross_ok = ck_outside == ck_jsonl["alive"] - ck_jsonl["converged"]

    rec = {
        "config": {
            "nodes": 100_000_000,
            "topology": "erdos_renyi(avg_degree=8.0)",
            "directed_edges": 799_999_952,
            "algorithm": "push-sum fanout-all diffusion",
            "dtype": "float32",
            "predicate": f"global tol={args.tol} (non-sticky, streak 3)",
            "edge_chunks": 6,
            "seed": 0,
            "checkpoints": "every 10 rounds (--auto-resume 12 armed, "
                           "never needed)",
        },
        "rounds": rounds,
        "converged": converged,
        "certification": {
            "device": f"round {rounds} record: converged == alive == "
                      f"{last['alive']} (every healthy node within tol "
                      "of the mass-conserving mean for 3 consecutive "
                      "rounds, evaluated on device each round)",
            "host_cross_check": {
                "checkpoint_round": ck_round,
                "recomputed_mean": mean,
                "recomputed_max_err": float(err.max()),
                "nodes_outside_tol": ck_outside,
                "matches_device_record": bool(cross_ok),
            },
        },
        "estimate_error_final": f"<= {args.tol} (certified on device; "
                                "round-126 spread ratio_max-ratio_min = "
                                f"{last['ratio_max'] - last['ratio_min']:.2e})",
        "ms_per_round_mean": 84_000,
        "wall_s_rounds_approx": round(rounds * 84.0),
        "timing_method": "record-timeline (round 10 at 05:11, round 126 "
                         "at 07:53 file mtime -> ~84 s/round incl. "
                         "checkpoint pauses); the final state fetch hung "
                         "on a tunnel RPC stall after certification, so "
                         "no CLI wall line exists",
        "w_underflow_total": 0,
        "backend": "tpu (v5e single chip)",
        "notes": [
            "VERDICT r4 #1 done: round 4 crossed the memory wall but "
            "stopped at a 14-round budget (err 0.205); this run drives "
            "the same config (seed 0 - identical trajectory, extended) "
            "to certified convergence at 1e8 nodes on one chip - the "
            "capability Program.fs:101-131 claims.",
            "per-round records in pushsum_100M_converged.jsonl; error "
            "contraction ~0.93-0.95/round after the transient (spread "
            "0.997 -> 1.7e-4 over 126 rounds)",
            "delivery: 6-chunk edge-sliced scatter. The single-chip "
            "routed delivery does not fit at 100M (10M plan tables "
            "measure 6.8 GB -> ~69 GB at 800M edges vs 15.75 GB HBM); "
            "the r5 SHARDED routed path divides tables by the shard "
            "count (~8.6 GB/shard on a v5e-8) and is the designed cure "
            "- artifacts/sharded_routed_assessment.json",
            "rounds 1-14 match round 4's budget-run trajectory exactly "
            "(same seed), tying the two artifacts together",
        ],
    }
    with open(os.path.join(REPO, args.out), "w") as fh:
        json.dump(rec, fh, indent=1)
    print(json.dumps(rec)[:1500], flush=True)
    assert converged and cross_ok


if __name__ == "__main__":
    main()
