"""Assemble the certified 100M push-sum artifact (VERDICT r4 #1).

The run itself is driven by the CLI (checkpoints + --auto-resume across
watchdog kills); this script distills its metrics JSONL + stdout log
into artifacts/pushsum_100M_diffusion.json, REPLACING round 4's
14-round budget record with the converged certification.

Usage: python experiments/pushsum_100m_artifact.py \
    [--log /tmp/pushsum100m.log] [--jsonl artifacts/pushsum_100M_converged.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--log", default="/tmp/pushsum100m.log")
    ap.add_argument("--jsonl",
                    default="artifacts/pushsum_100M_converged.jsonl")
    ap.add_argument("--out", default="artifacts/pushsum_100M_diffusion.json")
    ap.add_argument("--tol", type=float, default=1e-4)
    args = ap.parse_args()

    recs = [json.loads(line)
            for line in open(os.path.join(REPO, args.jsonl))]
    last = recs[-1]
    log = open(args.log).read()
    m_wall = re.search(r"Convergence Time: ([\d.]+) ms", log)
    m_tail = re.search(
        r"rounds: (\d+)\s+converged: (\w+).*?compile: ([\d.]+) ms", log)
    m_err = re.search(r"max \|s/w - mean\| = ([\d.e+-]+)", log)
    assert m_tail, "CLI result line not found — run still going?"
    rounds = int(m_tail.group(1))
    converged = m_tail.group(2) == "True"
    err = float(m_err.group(1)) if m_err else None
    wall_ms = float(m_wall.group(1)) if m_wall else None

    rec = {
        "config": {
            "nodes": 100_000_000,
            "topology": "erdos_renyi(avg_degree=8.0)",
            "directed_edges": 799_999_952,
            "algorithm": "push-sum fanout-all diffusion",
            "dtype": "float32",
            "predicate": f"global tol={args.tol}",
            "edge_chunks": 6,
            "checkpoints": "every 10 rounds (artifacts/pushsum100m_ck, "
                           "--auto-resume 12 armed)",
        },
        "rounds": rounds,
        "converged": converged,
        "estimate_error_final": err,
        "tol": args.tol,
        "wall_ms": wall_ms,
        "ms_per_round": round(wall_ms / max(rounds, 1), 1)
        if wall_ms else None,
        "compile_ms": float(m_tail.group(3)),
        "final_chunk_record": last,
        "backend": "tpu (v5e single chip)",
        "notes": [
            "VERDICT r4 #1: round 4 crossed the memory wall but stopped "
            "at a 14-round budget (err 0.205); this run drives the same "
            "config (seed 0 — identical trajectory, extended) to "
            "certification: every alive node within tol of the "
            "mass-conserving mean for 3 consecutive rounds "
            "(non-sticky predicate), the capability Program.fs:101-131 "
            "claims, at 1e8 nodes on one chip.",
            "per-round records in pushsum_100M_converged.jsonl; error "
            "contraction ~0.93-0.95/round after the transient "
            "(ratio spread 0.997 -> tol over the run)",
            "rounds ran ~55-90 s each: the 6-chunk edge-sliced scatter "
            "delivery (the single-chip routed delivery does not fit at "
            "100M: the 10M plan tables measure 6.8 GB -> ~69 GB at "
            "800M edges vs 15.75 GB HBM; the r5 SHARDED routed path "
            "divides tables by the shard count — ~8.6 GB/shard on a "
            "v5e-8 — and is the designed cure, "
            "artifacts/sharded_routed_assessment.json)",
            "w_underflow 0 throughout (fanout-all has no receipt dry "
            "spells by construction)",
        ],
    }
    with open(os.path.join(REPO, args.out), "w") as fh:
        json.dump(rec, fh, indent=1)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
