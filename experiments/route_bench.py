"""On-chip throughput of the routed permutation pipeline (ops/).

Builds a random pair permutation of --pairs units, compiles the plan,
and times apply_plan amortized inside one fori_loop dispatch (memory:
tpu-rig-run-discipline).  Compares against the segment_sum scatter
floor measured by route_probe2 (~7 ns/element).

Usage: python experiments/route_bench.py [--pairs 1064960] [--check]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from gossipprotocol_tpu.ops.plan import build_route_plan
from gossipprotocol_tpu.ops.exec import device_plan, apply_plan
# registers the DevicePlan pytree (geometry static, tables leaves) —
# without it tree.map would asarray the geometry ints too
import gossipprotocol_tpu.ops.delivery  # noqa: F401


def sync(x):
    return float(jax.device_get(jnp.sum(x.ravel()[:8].astype(jnp.float32))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pairs", type=int, default=130 * 8192)
    ap.add_argument("--check", action="store_true")
    ap.add_argument("--repeat", type=int, default=16)
    args = ap.parse_args()
    rng = np.random.default_rng(0)
    m = args.pairs
    print(f"device: {jax.devices()[0]}  pairs={m}", flush=True)

    t0 = time.perf_counter()
    perm = rng.permutation(m).astype(np.int64)
    t_perm = time.perf_counter() - t0
    t0 = time.perf_counter()
    plan = build_route_plan(perm, m_in=m, unit=2)
    t_plan = time.perf_counter() - t0
    print(f"plan: stages={len(plan.stages)} K={plan.final.k} "
          f"built in {t_plan:.1f}s (+{t_perm:.1f}s perm)", flush=True)
    # device_plan now returns host tables; upload explicitly — closing
    # the jit below over numpy leaves would embed them as jaxpr
    # constants (the "never close jit over GB tables" pitfall)
    dp = jax.tree.map(jnp.asarray, device_plan(plan))

    nt = plan.nt_in
    x = jnp.asarray(rng.standard_normal(nt * 16384), jnp.float32)

    if args.check:
        y = np.asarray(jax.jit(lambda v: apply_plan(dp, v))(x))
        k = np.arange(m)
        xh = np.asarray(jax.device_get(x))
        assert np.array_equal(y[k * 2], xh[perm * 2]), "even lane mismatch"
        assert np.array_equal(y[k * 2 + 1], xh[perm * 2 + 1]), "odd lane"
        print("on-chip: exact", flush=True)

    R = args.repeat

    @jax.jit
    def loop(x):
        def body(i, v):
            y = apply_plan(dp, v)
            return y[: nt * 16384] * (1.0 + i.astype(jnp.float32) * 0.0)
        return jax.lax.fori_loop(0, R, body, x)

    def timed(fn, repeats=3):
        fn()
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t = timed(lambda: sync(loop(x))) / R
    print(f"apply_plan: {t*1e3:9.3f} ms  {t/m*1e9:6.3f} ns/pair  "
          f"(scatter floor ~14 ns/pair for 2 streams)", flush=True)


if __name__ == "__main__":
    main()
