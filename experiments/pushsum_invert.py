"""Measure scatter→gather inversion of push-sum (s, w) delivery.

Round 3's gossip inversion (experiments/gather_invert.py) removed the
scatter because hit *counts* need no values: receivers recompute their
neighbors' draws from the counter-based PRNG and count matches — zero
data moves from sender rows to receiver rows. Push-sum delivery does move
data — each sender ships ``(s/2, w/2)`` to its drawn target
(``Program.fs:125-128``'s halve-and-forward, vectorized) — so the
inversion cannot be data-free, but it can swap the *kind* of data
movement: instead of two uniform-random scatter-adds (read-modify-write
traffic XLA serializes into the "scatter floor"), the receiver gathers
its neighbors' values at **static** indices (the dense table, a topology
constant) and keeps only the slots whose recomputed draw points back at
itself:

    in_s_i = Σ_k [ slot(nbr_k) == rev[i,k] ] · s[nbr_k] / 2    (w alike)

One [N, max_deg, 2] gather at fixed indices + elementwise compare/reduce
replaces both segment_sums. The bet was that gathers (no write
conflicts) beat random-write scatters.

MEASURED OUTCOME (TPU v5e, 1M Erdős–Rényi, max_deg 24): the bet LOSES
9x — 137.7 (invert) vs 15.1 (scatter) ms/round. Decomposition: the
draw recompute + compare alone is 3.9 ms (the part that made gossip's
inversion win 3.6x), but the [N, max_deg] random-index value gather is
~135 ms stacked — and two flat [N, max_deg] gathers are 2.6x worse
(370 ms), so stacking was right, the gather itself is the wall. XLA
lowers random-index gathers as badly as random scatters on this
hardware; inversion pays exactly when the receiver reconstructs the
message without reading sender values. Kept in the engine as a
validated negative (`--delivery invert`); scatter stays the default.

Exactness: the delivered multiset is identical to the scatter path's
whenever every sender with a live target delivers — the engine's
``all_alive`` / ``targets_alive`` fast-path regimes (no faults mid-run).
Unlike the gossip histogram (ints, bitwise-equal), the float *sum order*
differs from ``segment_sum``'s, so trajectories agree to accumulation
order, not bitwise — which is why the engine exposes this as an explicit
``delivery`` choice rather than an on-device auto-switch.

This script measures the raw kernels at BENCH scale on the push-sum
north-star graph family (Erdős–Rényi, avg degree 8) and checks
agreement: elementwise ulp-closeness and conservation of the delivered
mass.

Usage:  python experiments/pushsum_invert.py [--nodes 1000000]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from gossipprotocol_tpu import build_topology
from gossipprotocol_tpu.protocols.gossip import inverted_dense
from gossipprotocol_tpu.protocols.pushsum import received_by_inversion
from gossipprotocol_tpu.protocols.sampling import (
    device_topology, sample_neighbors,
)


def timed(fn, repeats=5):
    fn()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def sync(x):
    return float(jax.device_get(jnp.sum(jnp.asarray(x, jnp.float32))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1_000_000)
    ap.add_argument("--topology", default="erdos_renyi")
    args = ap.parse_args()

    topo = build_topology(args.topology, args.nodes, seed=0)
    n = topo.num_nodes
    nbrs = device_topology(topo, dense=True)
    key = jax.random.key(0)
    print(f"nodes={n} max_deg={nbrs.table.shape[1]} "
          f"backend={jax.default_backend()}")

    t0 = time.perf_counter()
    nbrs_inv = inverted_dense(topo)
    print(f"reverse-slot table build: {(time.perf_counter()-t0)*1e3:.0f} ms "
          "(host, once; shared with gossip's inversion)")

    # mid-run-looking state: distinct per-node values so a wrong slot or a
    # transposed index cannot cancel out in the comparison
    s = (jnp.arange(n, dtype=jnp.float32) % 1009) / 1009.0 + 0.5
    w = 1.0 + (jnp.arange(n, dtype=jnp.float32) % 313) / 313.0

    valid = nbrs.degree > 0

    # --- scatter delivery (the engine's current path) ---------------------
    @jax.jit
    def recv_scatter(key, s, w):
        targets, v = sample_neighbors(nbrs, n, key)
        s_sent = jnp.where(v, s * 0.5, 0)
        w_sent = jnp.where(v, w * 0.5, 0)
        return (
            jax.ops.segment_sum(s_sent, targets, num_segments=n),
            jax.ops.segment_sum(w_sent, targets, num_segments=n),
        )

    # --- gather-inverted delivery ----------------------------------------
    @jax.jit
    def recv_gather(key, s, w):
        return received_by_inversion(nbrs_inv, key, s, w)

    # agreement: scalar verdicts on device (full 1M+ fetches through the
    # tunnel cost minutes)
    @jax.jit
    def check(key, s, w):
        a_s, a_w = recv_scatter(key, s, w)
        b_s, b_w = recv_gather(key, s, w)
        # ulp-scale disagreement only (summation order); values are O(1)
        # and in-degrees are O(max_deg), so absolute tolerance is safe
        close = jnp.all(jnp.abs(a_s - b_s) <= 1e-4) & jnp.all(
            jnp.abs(a_w - b_w) <= 1e-4
        )
        sent_s = jnp.sum(jnp.where(valid, s, 0)) * 0.5
        cons = jnp.abs(jnp.sum(b_s) - sent_s) / sent_s
        return close, cons

    close, cons = jax.device_get(check(key, s, w))
    print(f"elementwise agreement (atol 1e-4): {bool(close)}")
    print(f"delivered-mass relative drift    : {float(cons):.2e}")
    assert bool(close), "inversion must reproduce the scatter delivery"

    R = 64

    def loop(recv):
        @jax.jit
        def run(key, s, w):
            def body(i, sw):
                s_, w_ = sw
                k = jax.random.fold_in(key, i)
                in_s, in_w = recv(k, s_, w_)
                # fold the received mass back so the loop carries a data
                # dependency (XLA must run every round)
                return s_ * 0.5 + in_s, w_ * 0.5 + in_w
            return jax.lax.fori_loop(0, R, body, (s, w))
        return run

    loop_scatter = loop(recv_scatter)
    loop_gather = loop(recv_gather)

    t_scatter = timed(lambda: sync(loop_scatter(key, s, w)[0])) / R
    t_gather = timed(lambda: sync(loop_gather(key, s, w)[0])) / R
    print(f"scatter delivery : {t_scatter*1e3:8.2f} ms/round")
    print(f"gather inversion : {t_gather*1e3:8.2f} ms/round")
    print(f"speedup          : {t_scatter/t_gather:8.2f}x")


if __name__ == "__main__":
    main()
