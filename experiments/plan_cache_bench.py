"""Routed-plan cache: cold build vs cached load at scale (VERDICT r4 #2).

Round 4 measured the 10M routed-delivery plan build at 2 240 s of
single-core host work — 5x the entire 71-round scatter run it replaces —
making the 21.2x routed kernel (artifacts/routed_diffusion_10m.json) a
benchmark fact, not a usable capability. This script measures the two
fixes landed in round 5 on the same 10M power-law topology:

  1. the fused native tile router (native/routecolor.cpp
     route_tiles_full: bijection completion + Euler coloring + index
     assembly in one C++ pass) cutting the cold build itself, and
  2. the content-addressed disk cache (ops/plancache.py) that turns
     every repeat run into an npz load.

Usage: python experiments/plan_cache_bench.py [--nodes 10000000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=10_000_000)
    ap.add_argument("--m", type=int, default=4)
    ap.add_argument("--out", default="artifacts/routed_plan_cache_10m.json")
    args = ap.parse_args()

    from gossipprotocol_tpu import build_topology
    from gossipprotocol_tpu.ops import plancache

    t0 = time.perf_counter()
    topo = build_topology("powerlaw", args.nodes, seed=7, m=args.m)
    topo_s = time.perf_counter() - t0
    print(f"topology: {topo.num_directed_edges} directed edges "
          f"({topo_s:.0f}s)", flush=True)

    cache_dir = plancache.default_cache_dir()
    path = plancache.entry_path(cache_dir, plancache.cache_key(topo))
    if os.path.exists(path):
        os.unlink(path)  # measure a genuinely cold build

    t0 = time.perf_counter()
    rd, state = plancache.routed_delivery_cached(
        topo, cache_dir=cache_dir, device=False,
        progress=lambda m: print(m, flush=True))
    cold_s = time.perf_counter() - t0
    assert state == "miss"
    entry_mb = os.path.getsize(path) / 1e6
    print(f"cold build+save: {cold_s:.1f}s, entry {entry_mb:.0f} MB",
          flush=True)

    del rd
    t0 = time.perf_counter()
    rd2, state2 = plancache.routed_delivery_cached(
        topo, cache_dir=cache_dir, device=False)
    warm_s = time.perf_counter() - t0
    assert state2 == "hit"
    print(f"cached load: {warm_s:.1f}s", flush=True)

    rec = {
        "nodes": topo.num_nodes,
        "topology": f"powerlaw (BA m={args.m})",
        "edges_directed": int(topo.num_directed_edges),
        "build_s_round4": 2240.5,
        "build_s_cold": round(cold_s, 1),
        "load_s_cached": round(warm_s, 1),
        "cache_entry_mb": round(entry_mb, 1),
        "speedup_repeat_runs": round(2240.5 / warm_s, 1),
        "host": "1-core VM (the round-4 number's own host)",
        "notes": [
            "cold path includes writing the cache entry; cached path is "
            "the full npz load + RoutedDelivery reassembly (host side; "
            "the one-time device upload is shared by both paths and "
            "excluded, as in round 4's build_s)",
            "cache key: blake2b adjacency content hash "
            "(plancache.cache_key) + plancache.FORMAT_VERSION",
            "cold build improvement over round 4 comes from "
            "native/routecolor.cpp route_tiles_full (fused completion + "
            "coloring + index assembly)",
        ],
    }
    with open(os.path.join(REPO, args.out), "w") as fh:
        json.dump(rec, fh, indent=1)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
