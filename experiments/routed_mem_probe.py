"""Memory breakdown of the routed diffusion round (10M OOM diagnosis).

The 10M routed round failed AOT compile needing 52.8 GB vs 16 GB HBM.
This probe compiles the same chunk program at a smaller scale and prints
XLA's memory analysis (arguments, outputs, temporaries, generated code)
plus a host-side inventory of the plan tables, so the dominant term is
measured, not guessed.

Usage: python experiments/routed_mem_probe.py [--nodes 2000000]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from gossipprotocol_tpu import build_topology
from gossipprotocol_tpu.engine.driver import (
    RunConfig, build_protocol, device_arrays, make_chunk_runner,
)


def nbytes_tree(tree):
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(tree)
               if hasattr(x, "nbytes"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2_000_000)
    ap.add_argument("--m", type=int, default=4)
    args = ap.parse_args()
    topo = build_topology("powerlaw", args.nodes, seed=7, m=args.m)
    cfg = RunConfig(algorithm="push-sum", fanout="all", predicate="global",
                    tol=1e-4, seed=11, delivery="routed")
    t0 = time.perf_counter()
    nbrs = device_arrays(topo, cfg)
    print(f"plan build: {time.perf_counter()-t0:.0f}s", flush=True)

    for name in ("plan_in", "plan_m", "plan_out"):
        plans = getattr(nbrs, name)
        tot = sum(nbytes_tree(p) for p in plans)
        geo = [
            (f"stages={[(s.b, s.cr, s.o, s.tau_slab) for s in p.stages]}"
             f" K={p.final.k} nt={p.nt_in}")
            for p in plans
        ]
        print(f"{name}: {len(plans)} plans, {tot/1e9:.2f} GB  {geo}",
              flush=True)
    print(f"realmask+degree: {nbrs.realmask.nbytes/1e9:.2f} GB", flush=True)
    print(f"plan total: {nbytes_tree(nbrs)/1e9:.2f} GB", flush=True)

    state, core, done, extra, _fl = build_protocol(topo, cfg)
    runner = make_chunk_runner(core, done, extra)
    lowered = runner.lower(state, nbrs, jax.random.PRNGKey(0), jnp.int32(4))
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    print("memory analysis:", ma, flush=True)


if __name__ == "__main__":
    main()
