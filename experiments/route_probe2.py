"""Second routing probe: does blocking beat the scatter floor?

route_probe.py showed XLA's random scatter runs at ~7 ns/element into a
1M-row (4 MB) target and the practical stream ceiling of this rig is
~46 GB/s.  The static-edge delivery plan needs to know how scatter and
gather per-element cost scale with the *working-set size* — if a scatter
into a 64K-row window is much cheaper per element, then sorting edges by
dst-block at build time (free: the edge list is static) turns one huge
scatter into B cache-friendly small ones, pure XLA, no Mosaic.

Probes (all amortized over R iterations in one dispatch):
  scatter  E=8M -> N in {16K, 64K, 256K, 1M, 4M}
  gather   E=8M random ids from tables of the same sizes
  gather   E=8M SORTED ids from a 1M table (the "expand" op)
  repeat   share[1M] by static degrees to 8M (jnp.repeat fixed total)
  scan-of-blocked-scatters: the actual candidate delivery, 8M msgs
      pre-partitioned into B blocks of a 1M-row target

Usage: python experiments/route_probe2.py [--e 8000000] [--n 1000000]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

R = 32


def sync(x):
    return float(jax.device_get(jnp.sum(x.ravel()[:8].astype(jnp.float32))))


def timed(fn, repeats=3):
    fn()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench(name, op, *carry, nelem):
    @jax.jit
    def run(*c):
        return jax.lax.fori_loop(0, R, lambda i, c: op(i, *c), c)

    t = timed(lambda: sync(run(*carry)[0])) / R
    print(f"{name:52s} {t*1e3:9.3f} ms  {t/nelem*1e9:7.2f} ns/elem",
          flush=True)
    return t


def chain(v, scalar):
    return v * (1.0 + scalar * 1e-30)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--e", type=int, default=8_000_000)
    ap.add_argument("--n", type=int, default=1_000_000)
    args = ap.parse_args()
    E, N = args.e, args.n
    rng = np.random.default_rng(0)
    print(f"device: {jax.devices()[0]}  E={E} N={N}  R={R}", flush=True)

    vals0 = jnp.asarray(rng.standard_normal(E), jnp.float32)

    # ---- scatter cost vs target size ------------------------------------
    for n_t in (16_384, 65_536, 262_144, 1_048_576, 4_194_304):
        tgt = jnp.asarray(rng.integers(0, n_t, size=E), jnp.int32)

        def op(i, v, tgt=tgt, n_t=n_t):
            out = jax.ops.segment_sum(v, tgt, num_segments=n_t)
            return (chain(v, out[0]),)

        bench(f"scatter E=8M -> target {n_t//1024}K rows", op, vals0,
              nelem=E)

    # ---- gather cost vs table size --------------------------------------
    for n_t in (16_384, 65_536, 262_144, 1_048_576, 4_194_304):
        table = jnp.asarray(rng.standard_normal(n_t), jnp.float32)
        idx = jnp.asarray(rng.integers(0, n_t, size=E), jnp.int32)

        def op(i, tb, idx=idx):
            out = jnp.take(tb, idx)
            return (chain(tb, out[0]),)

        bench(f"gather E=8M from table {n_t//1024}K rows", op, table,
              nelem=E)

    # ---- sorted gather (the expand) -------------------------------------
    share = jnp.asarray(rng.standard_normal(N), jnp.float32)
    idx_sorted = jnp.sort(
        jnp.asarray(rng.integers(0, N, size=E), jnp.int32))

    def op_sg(i, tb):
        out = jnp.take(tb, idx_sorted)
        return (chain(tb, out[0]),)

    bench("gather E=8M SORTED ids from 1M table", op_sg, share, nelem=E)

    # jnp.repeat with static degrees, fixed total
    deg = rng.integers(0, 16, size=N)
    deg = (deg * (E / deg.sum())).astype(np.int64)
    deg[0] += E - deg.sum()
    deg_dev = jnp.asarray(deg, jnp.int32)

    def op_rep(i, tb):
        out = jnp.repeat(tb, deg_dev, total_repeat_length=E)
        return (chain(tb, out[0]),)

    bench("repeat 1M -> 8M (static total)", op_rep, share, nelem=E)

    # ---- the candidate: scan of blocked scatters ------------------------
    # messages pre-partitioned by dst block (static, build-time); equal
    # block sizes by construction here (real builder pads)
    for B in (4, 16, 64):
        nb, eb = N // B, E // B
        # dst within block b is any row of that block
        loc = jnp.asarray(rng.integers(0, nb, size=(B, eb)), jnp.int32)
        v_b = jnp.asarray(rng.standard_normal((B, eb)), jnp.float32)

        def op_blk(i, v, loc=loc, nb=nb):
            def body(carry, xs):
                vv, ll = xs
                out = jax.ops.segment_sum(vv, ll, num_segments=nb)
                return carry + out[0], out
            s, outs = jax.lax.scan(body, 0.0, (v, loc))
            return (chain(v, s),)

        bench(f"scan of {B} blocked scatters (target {nb//1024}K)",
              op_blk, v_b, nelem=E)

    # same but unrolled python loop (XLA sees static slices)
    B = 16
    nb, eb = N // B, E // B
    loc = jnp.asarray(rng.integers(0, nb, size=(B, eb)), jnp.int32)
    v_b = jnp.asarray(rng.standard_normal((B, eb)), jnp.float32)

    def op_unroll(i, v):
        s = 0.0
        for b in range(B):
            out = jax.ops.segment_sum(v[b], loc[b], num_segments=nb)
            s = s + out[0]
        return (chain(v, s),)

    bench(f"unrolled {B} blocked scatters (target {nb//1024}K)",
          op_unroll, v_b, nelem=E)


if __name__ == "__main__":
    main()
