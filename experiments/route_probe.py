"""Probe the primitives a static-routing delivery kernel would stand on.

The scatter floor (README "Roofline") is XLA's serialized lowering of
`segment_sum` with uniform-random segment ids.  Because the diffusion
edge list — and push-sum's dense neighbor table — are *static*, delivery
is really `out = segment_sum(vals[perm], sorted_dst)` with a
build-time-known permutation `perm`.  A permutation decomposes into
VMEM-tile-local shuffles (take_along_axis passes, Hall routing) plus one
block transpose through HBM staging — all vectorizable.  This probe
measures, on the real chip, every primitive that plan needs.

Timing discipline (memory: tpu-rig-run-discipline): the axon tunnel adds
~100 ms per host round-trip, so every op is amortized over R iterations
inside ONE jitted `fori_loop` dispatch, with a multiplicative carry so
XLA cannot hoist the op out of the loop.  Support probes (pallas dim-0
gather, VMEM residency) are single calls — pass/fail is the datum.

Usage: python experiments/route_probe.py [--e 8000000] [--n 1000000]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

R = 32  # amortization iterations per dispatch


def sync(x):
    return float(jax.device_get(jnp.sum(x.ravel()[:8].astype(jnp.float32))))


def timed(fn, repeats=3):
    fn()  # compile + program load + upload
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def report(name, secs_per_op, nbytes):
    gbps = nbytes / secs_per_op / 1e9
    print(f"{name:46s} {secs_per_op*1e3:9.3f} ms  {gbps:8.1f} GB/s",
          flush=True)


def loop(op, *carry):
    """R iterations of `carry = op(i, carry)` in one dispatch."""

    @jax.jit
    def run(*c):
        def body(i, c):
            return op(i, *c)
        return jax.lax.fori_loop(0, R, body, c)

    return run, carry


def bench(name, op, nbytes, *carry):
    run, c = loop(op, *carry)
    t = timed(lambda: sync(run(*c)[0])) / R
    report(name, t, nbytes)
    return t


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--e", type=int, default=8_000_000)
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--skip-pallas", action="store_true")
    args = ap.parse_args()
    E, N = args.e, args.n
    rng = np.random.default_rng(0)
    print(f"device: {jax.devices()[0]}  E={E} N={N}  R={R}", flush=True)

    tgt = jnp.asarray(rng.integers(0, N, size=E), jnp.int32)
    tgt_sorted = jnp.sort(tgt)
    vals0 = jnp.asarray(rng.standard_normal(E), jnp.float32)

    def perturb(i, v):
        return v * (1.0 + i.astype(jnp.float32) * 1e-12)

    def chain(v, scalar):
        # fold a result scalar back into the carry so XLA cannot DCE the op
        return v * (1.0 + scalar * 1e-30)

    # elementwise stream baseline: what "fast" means on this stack
    bench("elementwise multiply (stream baseline)",
          lambda i, v: (perturb(i, v),), 8 * E, vals0)

    def op_scat(i, v):
        out = jax.ops.segment_sum(v, tgt, num_segments=N)
        return (chain(v, out[0]),)
    bench("segment_sum random ids (baseline)", op_scat,
          8 * E + 4 * N, vals0)

    vals2 = jnp.stack([vals0, vals0], axis=-1)

    def op_scat2(i, v):
        out = jax.ops.segment_sum(v, tgt, num_segments=N)
        return (chain(v, out[0, 0]),)
    bench("segment_sum random ids [E,2] stacked", op_scat2,
          12 * E + 8 * N, vals2)

    def op_sorted(i, v):
        out = jax.ops.segment_sum(v, tgt_sorted, num_segments=N,
                                  indices_are_sorted=True)
        return (chain(v, out[0]),)
    bench("segment_sum SORTED ids", op_sorted, 8 * E + 4 * N, vals0)

    def op_cumsum(i, v):
        out = jnp.cumsum(v)
        return (chain(v, out[-1]),)
    bench("cumsum over E", op_cumsum, 8 * E, vals0)

    # ---- XLA batched take_along_axis ------------------------------------
    W = 4096 * 128
    T = max(1, E // W)
    data3 = jnp.asarray(rng.standard_normal((T, 4096, 128)), jnp.float32)
    idx_r = jnp.asarray(rng.integers(0, 4096, size=(T, 4096, 128)), jnp.int32)
    idx_c = jnp.asarray(rng.integers(0, 128, size=(T, 4096, 128)), jnp.int32)
    nb = T * W * 4 * 3

    bench("XLA take_along_axis dim0 (sublanes)",
          lambda i, d: (jnp.take_along_axis(d, idx_r, axis=1),),
          nb, data3)
    bench("XLA take_along_axis dim1 (lanes)",
          lambda i, d: (jnp.take_along_axis(d, idx_c, axis=2),),
          nb, data3)

    B, P = 16, W // 16
    stg = jnp.asarray(rng.standard_normal((T, B, P)), jnp.float32)
    bench("XLA [T,B,P]->[B,T,P] transpose",
          lambda i, d: (jnp.transpose(d, (1, 0, 2)).transpose(1, 0, 2)
                        * 1.0000001,),
          T * B * P * 8, stg)

    if args.skip_pallas:
        return

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def copy_kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0

    # VMEM residency ceiling for a single resident block
    for mb in (2, 4, 8, 12, 16):
        rows = mb * 1024 * 1024 // (128 * 4)
        xb = jnp.ones((rows, 128), jnp.float32)
        try:
            y = pl.pallas_call(
                copy_kernel,
                out_shape=jax.ShapeDtypeStruct(xb.shape, xb.dtype),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            )(xb)
            sync(y)
            print(f"VMEM probe {mb:3d} MB resident block: OK", flush=True)
        except Exception as ex:  # noqa: BLE001
            print(f"VMEM probe {mb:3d} MB: FAILED ({type(ex).__name__})",
                  flush=True)
            break

    # dim-0 (sublane) gather support, by row count
    for rows in (8, 256, 1024, 4096):
        xg = jnp.ones((rows, 128), jnp.float32)
        ig = jnp.asarray(rng.integers(0, rows, size=(rows, 128)), jnp.int32)

        def g0(x_ref, i_ref, o_ref):
            o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=0)

        try:
            y = pl.pallas_call(
                g0,
                out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                          pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            )(xg, ig)
            sync(y)
            print(f"pallas dim0 gather rows={rows:5d}: OK", flush=True)
        except Exception as ex:  # noqa: BLE001
            print(f"pallas dim0 gather rows={rows:5d}: FAILED "
                  f"({type(ex).__name__})", flush=True)

    # wide-row (cross-vreg lane) gather support
    for cols in (128, 512, 4096):
        xg = jnp.ones((128, cols), jnp.float32)
        ig = jnp.asarray(rng.integers(0, cols, size=(128, cols)), jnp.int32)

        def g1(x_ref, i_ref, o_ref):
            o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=1)

        try:
            y = pl.pallas_call(
                g1,
                out_shape=jax.ShapeDtypeStruct((128, cols), jnp.float32),
                in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                          pl.BlockSpec(memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            )(xg, ig)
            sync(y)
            print(f"pallas dim1 gather cols={cols:5d}: OK", flush=True)
        except Exception as ex:  # noqa: BLE001
            print(f"pallas dim1 gather cols={cols:5d}: FAILED "
                  f"({type(ex).__name__})", flush=True)

    # amortized pallas dim1 gather throughput at tile scale
    grid_call = pl.pallas_call(
        lambda x_ref, i_ref, o_ref: o_ref.__setitem__(
            0, jnp.take_along_axis(x_ref[0], i_ref[0], axis=1)),
        grid=(T,),
        out_shape=jax.ShapeDtypeStruct((T, 4096, 128), jnp.float32),
        in_specs=[
            pl.BlockSpec((1, 4096, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 4096, 128), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 4096, 128), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
    )
    try:
        bench("pallas dynamic_gather dim1 (tiled)",
              lambda i, d: (grid_call(d, idx_c),), nb, data3)
    except Exception as ex:  # noqa: BLE001
        print(f"pallas dynamic_gather dim1 (tiled): FAILED "
              f"({type(ex).__name__})", flush=True)

    # pallas HBM->VMEM->HBM streaming copy: the achievable stream ceiling
    big_rows = 64 * 1024 * 1024 // (128 * 4)  # 64 MB
    xs = jnp.ones((big_rows, 128), jnp.float32)
    stream_call = pl.pallas_call(
        copy_kernel,
        grid=(big_rows // 1024,),
        out_shape=jax.ShapeDtypeStruct((big_rows, 128), jnp.float32),
        in_specs=[pl.BlockSpec((1024, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1024, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
    )
    bench("pallas grid stream copy 64MB",
          lambda i, d: (stream_call(d) * 1.0,),
          big_rows * 128 * 8, xs)


if __name__ == "__main__":
    main()
