"""Probe the inter-pass regrouping options for the routed delivery.

A routing pass emits, per input tile, B bucket runs that the next pass
must read bucket-major.  Two candidate mechanisms:

  (a) strided slab write: pallas output block (B, 1, CR, 128) over a
      [B, T, CR, 128] staging array — each grid step writes B strided
      chunks of CR*512 bytes; next pass reads contiguously.
  (b) contiguous write [T, B, CR, 128] + one XLA transpose to
      [B, T, CR, 128] between passes.

Measures both at delivery scale.  Also probes the minor-dim class
reduce (reshape [n, c] -> sum(-1)) the reduce stage relies on.

Usage: python experiments/slab_probe.py
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

R = 32


def sync(x):
    return float(jax.device_get(jnp.sum(x.ravel()[:8].astype(jnp.float32))))


def timed(fn, repeats=3):
    fn()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench(name, op, nbytes, *carry):
    @jax.jit
    def run(*c):
        return jax.lax.fori_loop(0, R, lambda i, c: op(i, *c), c)

    t = timed(lambda: sync(run(*carry)[0])) / R
    print(f"{name:52s} {t*1e3:9.3f} ms  {nbytes/t/1e9:6.1f} GB/s",
          flush=True)
    return t


def main():
    rng = np.random.default_rng(0)
    print(f"device: {jax.devices()[0]}", flush=True)

    # scale: ~8M f32 payload per pass (1M-node diffusion pair scale)
    T, B, CR = 512, 102, 1  # T tiles in, B buckets, CR rows per (b, t)
    x = jnp.asarray(rng.standard_normal((T, 128, 128)), jnp.float32)
    nbytes = T * 128 * 128 * 8  # read + write

    # (a) strided slab write from pallas
    def slab_kernel(x_ref, o_ref):
        tile = x_ref[0] * 2.0
        # write the tile's rows as B runs of CR rows (first B*CR rows are
        # real content here; the layout cost is what we measure)
        o_ref[:, 0] = tile[: B * CR].reshape(B, 1, CR, 128)[:, 0]

    slab = pl.pallas_call(
        slab_kernel,
        grid=(T,),
        out_shape=jax.ShapeDtypeStruct((B, T, CR, 128), jnp.float32),
        in_specs=[pl.BlockSpec((1, 128, 128), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((B, 1, CR, 128), lambda i: (0, i, 0, 0)),
    )

    try:
        def op_a(i, v):
            y = slab(v)
            return (v * (1.0 + y[0, 0, 0, 0] * 1e-30),)
        bench("pallas strided slab write (B,1,CR,128)", op_a, nbytes, x)
    except Exception as ex:  # noqa: BLE001
        print(f"slab write FAILED: {type(ex).__name__}: "
              f"{str(ex).splitlines()[0][:160]}", flush=True)

    # (b) contiguous write + XLA transpose
    def contig_kernel(x_ref, o_ref):
        o_ref[0] = (x_ref[0] * 2.0)[: B * CR].reshape(B, CR, 128)

    contig = pl.pallas_call(
        contig_kernel,
        grid=(T,),
        out_shape=jax.ShapeDtypeStruct((T, B, CR, 128), jnp.float32),
        in_specs=[pl.BlockSpec((1, 128, 128), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, B, CR, 128), lambda i: (i, 0, 0, 0)),
    )

    def op_b(i, v):
        y = contig(v)
        z = jnp.transpose(y, (1, 0, 2, 3))
        return (v * (1.0 + z[0, 0, 0, 0] * 1e-30),)

    bench("pallas contig write + XLA transpose", op_b, nbytes * 2, x)

    # minor-dim class reduce at delivery scale
    for c in (8, 32, 128):
        n = 8_000_000 // c
        seg = jnp.asarray(rng.standard_normal((n, c)), jnp.float32)

        def op_r(i, v):
            s = jnp.sum(v, -1)
            return (v * (1.0 + s[0] * 1e-30),)

        bench(f"reshape [n,{c}] minor-dim sum", op_r, n * c * 4, seg)


if __name__ == "__main__":
    main()
