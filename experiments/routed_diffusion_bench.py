"""Routed vs scatter diffusion round, on the real chip (VERDICT r3 #1).

Builds the BENCH power-law topology, compiles both round paths, and
times ms/round amortized in one fori_loop dispatch each (memory:
tpu-rig-run-discipline; dispatches sized under the remote watchdog).
Prints one JSON line with the measured rounds for the artifact.

Usage:
  python experiments/routed_diffusion_bench.py [--nodes 1000000] [--m 4]
      [--rounds 16] [--out artifacts/routed_diffusion.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from gossipprotocol_tpu import build_topology
from gossipprotocol_tpu.engine.driver import (
    RunConfig, build_protocol, device_arrays,
)


def sync(x):
    return float(jax.device_get(jnp.sum(x.ravel()[:8].astype(jnp.float32))))


def timed(fn, repeats=3):
    fn()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1_000_000)
    ap.add_argument("--m", type=int, default=4)
    ap.add_argument("--topology", default="powerlaw")
    ap.add_argument("--rounds", type=int, default=16)
    ap.add_argument("--deliveries", default="scatter,routed")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    print(f"device: {jax.devices()[0]}", flush=True)

    t0 = time.perf_counter()
    topo = build_topology(args.topology, args.nodes, seed=7, m=args.m,
                          avg_degree=8.0)
    print(f"topology: n={topo.num_nodes} edges={topo.num_directed_edges} "
          f"({time.perf_counter()-t0:.1f}s)", flush=True)

    results = {}
    for delivery in args.deliveries.split(","):
        cfg = RunConfig(algorithm="push-sum", fanout="all",
                        predicate="global", tol=1e-4, seed=11,
                        delivery=delivery)
        t0 = time.perf_counter()
        nbrs = device_arrays(topo, cfg)
        t_build = time.perf_counter() - t0
        state, core, _done, _extra, _fl = build_protocol(topo, cfg)
        key = jax.random.PRNGKey(0)
        R = args.rounds

        # nbrs must be a jit ARGUMENT: closing over the routed plan's
        # tables would embed GBs of int8 constants into the jaxpr and
        # stall tracing/compile for tens of minutes (measured)
        @jax.jit
        def loop(s, nb):
            def body(i, s):
                return core(s, nb, key)
            return jax.lax.fori_loop(0, R, body, s)

        t = timed(lambda: sync(loop(state, nbrs).s)) / R
        results[delivery] = dict(ms_per_round=t * 1e3,
                                 build_s=t_build)
        print(f"{delivery:8s}: {t*1e3:9.2f} ms/round "
              f"(delivery build {t_build:.1f}s)", flush=True)

    if "scatter" in results and "routed" in results:
        sp = results["scatter"]["ms_per_round"] / results[
            "routed"]["ms_per_round"]
        print(f"speedup: {sp:.2f}x", flush=True)
        results["speedup"] = sp
    rec = dict(nodes=args.nodes, topology=args.topology, m=args.m,
               rounds_timed=args.rounds, results=results,
               device=str(jax.devices()[0]))
    print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)


if __name__ == "__main__":
    main()
