"""Measure the scatter→gather inversion of gossip hit delivery (VERDICT r2 #5).

The engine's counter-based PRNG makes every node's draw a pure function of
(round key, node id), so a *receiver* can recompute its neighbors' draws
instead of the senders scatter-adding hits:

    hits_i = Σ_k [ slot(nbr_k(i)) == rev[i, k] ]

where ``slot(j) = threefry(key, j) % deg(j)`` is elementwise over the
static neighbor table (the neighbor ids, their degrees, and the position
``rev[i,k]`` of i within neighbor k's row are all topology constants), so
the whole hit pass is O(N·max_deg) elementwise work — **no scatter, no
gather, and under shard_map zero collectives** (each device computes its
own rows' hits from its own table shard).

The catch: this is exact only when every neighbor is actually spreading.
With ``keep_alive=True`` (the default and the reference's intent) that is
the steady state — once every node has heard, spreaders == everyone and
stays that way until global convergence; at BENCH scale (1M/10M imp3D)
~90+% of all rounds run in that regime. Before saturation the inversion
would need the sender's heard-bit, a [N·max_deg] random gather that costs
what the scatter does — so the engine compiles both deliveries and picks
per round with an on-device ``lax.cond`` on "all eligible spreading"
(``gossip_round_core(..., inverted=True)`` in protocols/gossip.py).

This script measures the raw kernels at BENCH scale: scatter delivery vs
gather-inverted delivery, plus their agreement (bitwise-equal hit
histograms by construction).

Usage:  python experiments/gather_invert.py [--nodes 1000000]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from gossipprotocol_tpu import build_topology
from gossipprotocol_tpu.protocols.sampling import (
    device_topology, sample_neighbors,
)
from gossipprotocol_tpu.protocols.gossip import hits_by_inversion, inverted_dense


def timed(fn, repeats=5):
    fn()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def sync(x):
    return float(jax.device_get(jnp.sum(jnp.asarray(x, jnp.float32))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1_000_000)
    args = ap.parse_args()

    topo = build_topology("imp3D", args.nodes, seed=0)
    n = topo.num_nodes
    nbrs = device_topology(topo, dense=True)
    key = jax.random.key(0)
    print(f"nodes={n} max_deg={nbrs.table.shape[1]} "
          f"backend={jax.default_backend()}")

    # static inversion tables (host-side, once per topology)
    t0 = time.perf_counter()
    nbrs_inv = inverted_dense(topo)
    build_s = time.perf_counter() - t0
    print(f"reverse-slot table build: {build_s*1e3:.0f} ms (host, once)")

    # --- scatter mode: draw + segment_sum (the pre-saturation delivery) --
    @jax.jit
    def hits_scatter(key):
        targets, valid = sample_neighbors(nbrs, n, key)
        return jax.ops.segment_sum(
            valid.astype(jnp.int32), targets, num_segments=n
        )

    # --- gather-inverted mode: recompute neighbors' draws, compare ------
    @jax.jit
    def hits_gather(key):
        return hits_by_inversion(nbrs_inv, key)

    # equality checked on device: fetching two full 10M histograms
    # through the tunnel costs minutes; a scalar verdict does not
    equal = bool(jax.device_get(
        jax.jit(lambda k: jnp.all(hits_scatter(k) == hits_gather(k)))(key)
    ))
    assert equal, "inversion must reproduce the scatter"
    print("hit histograms bitwise equal: True")

    # R iterations inside one program: a single dispatch through the
    # tunnel costs ~100 ms RTT, so per-kernel cost is only visible
    # amortized inside a fori_loop (same method as profile_round.py)
    R = 64

    @jax.jit
    def loop_scatter(key):
        def body(i, acc):
            k = jax.random.fold_in(key, i)
            return acc + hits_scatter(k)
        return jax.lax.fori_loop(0, R, body, jnp.zeros(n, jnp.int32))

    @jax.jit
    def loop_gather(key):
        def body(i, acc):
            k = jax.random.fold_in(key, i)
            return acc + hits_gather(k)
        return jax.lax.fori_loop(0, R, body, jnp.zeros(n, jnp.int32))

    t_scatter = timed(lambda: sync(loop_scatter(key))) / R
    t_gather = timed(lambda: sync(loop_gather(key))) / R
    print(f"scatter delivery : {t_scatter*1e3:8.2f} ms/round")
    print(f"gather inversion : {t_gather*1e3:8.2f} ms/round")
    print(f"speedup          : {t_scatter/t_gather:8.2f}x")


if __name__ == "__main__":
    main()
