"""Prove the persistent compile cache at 10M scale (VERDICT r3 #6).

Runs the 10M imp3D gossip config twice in FRESH subprocesses sharing one
persistent cache dir and records compile_ms for each: the first pays the
full XLA compile, the second should collapse to cache-hit + program load.
Writes artifacts/compile_cache_10m.json.

Usage: python experiments/compile_cache_proof.py [--nodes 10000000]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_once(nodes: int, cache_dir: str):
    env = dict(os.environ, GOSSIP_TPU_COMPILE_CACHE=cache_dir)
    t0 = time.perf_counter()
    out = subprocess.run(
        [sys.executable, "-m", "gossipprotocol_tpu", str(nodes), "imp3D",
         "gossip", "--seed", "0", "--chunk-rounds", "4096",
         "--compile-cache", cache_dir],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=1500,
    )
    wall = time.perf_counter() - t0
    m = re.search(r"compile: ([0-9.]+) ms", out.stdout)
    r = re.search(r"rounds: (\d+)", out.stdout)
    c = re.search(r"Convergence Time: ([0-9.]+) ms", out.stdout)
    assert out.returncode == 0, out.stdout + out.stderr
    return {
        "compile_ms": float(m.group(1)) if m else None,
        "rounds": int(r.group(1)) if r else None,
        "convergence_ms": float(c.group(1)) if c else None,
        "process_wall_s": round(wall, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=10_000_000)
    ap.add_argument("--out", default="artifacts/compile_cache_10m.json")
    args = ap.parse_args()
    cache = tempfile.mkdtemp(prefix="ccache_proof_")
    first = run_once(args.nodes, cache)
    print("first :", first, flush=True)
    second = run_once(args.nodes, cache)
    print("cached:", second, flush=True)
    rec = {
        "nodes": args.nodes,
        "topology": "imp3D",
        "cache_dir_fresh": True,
        "first_run": first,
        "cached_run": second,
        "compile_speedup": round(
            first["compile_ms"] / max(second["compile_ms"], 1e-9), 1)
        if first["compile_ms"] and second["compile_ms"] else None,
        "note": "fresh subprocesses sharing one persistent XLA cache dir; "
                "compile_ms includes remote (axon) program load, which the "
                "cache cannot remove — the XLA-compile component is what "
                "collapses",
    }
    with open(os.path.join(REPO, args.out), "w") as fh:
        json.dump(rec, fh, indent=1)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
