"""Render the curve artifacts as figures mirroring Report.pdf p.1-2.

Two PNGs into artifacts/:

  * ``curves_plot.png`` — TPU wall-clock to convergence vs node count
    (from ``curves_tpu_v5e1.csv``), one panel per algorithm;
  * ``oracle_plot.png`` — async-oracle event/hop counts vs node count
    (from ``oracle_curves.csv``): the reference's *shapes* (its wall-clock
    is hops x per-hop latency), reproduced mechanically.

Styling follows the repo-neutral dataviz method: categorical slots in
fixed order, thin 2px lines, recessive grid, direct end-labels (which
also satisfy the light-surface contrast relief rule for the yellow slot),
one y-axis per panel.

    python experiments/plot_curves.py
"""

from __future__ import annotations

import csv
import os
from collections import defaultdict

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts")

# categorical slots, fixed order (validated reference palette, light mode)
SLOT = {"line": "#2a78d6", "full": "#eb6834", "3D": "#1baf7a", "imp3D": "#eda100"}
SURFACE = "#fcfcfb"
INK = "#0b0b0b"
MUTED = "#898781"
GRID = "#e1e0d9"
BASELINE = "#c3c2b7"
TOPO_ORDER = ["line", "full", "3D", "imp3D"]


def _style_axis(ax, title, ylabel):
    ax.set_facecolor(SURFACE)
    ax.set_title(title, color=INK, fontsize=11, loc="left")
    ax.set_xlabel("nodes", color=MUTED, fontsize=9)
    ax.set_ylabel(ylabel, color=MUTED, fontsize=9)
    ax.grid(True, color=GRID, linewidth=0.6)
    for spine in ("top", "right"):
        ax.spines[spine].set_visible(False)
    for spine in ("left", "bottom"):
        ax.spines[spine].set_color(BASELINE)
    ax.tick_params(colors=MUTED, labelsize=8)


def _plot_series(ax, series, logy=False):
    import math

    ends = []
    for topo in TOPO_ORDER:
        if topo not in series:
            continue
        xs, ys = zip(*sorted(series[topo]))
        ax.plot(xs, ys, color=SLOT[topo], linewidth=2,
                marker="o", markersize=4, label=topo)
        ends.append((topo, xs[-1], ys[-1]))
    if not ends:
        return  # empty panel (restricted CSV) — render blank, don't crash
    if logy:
        ax.set_yscale("log")

    # direct end-labels (identity never color-alone), pushed apart when
    # final points land too close to read
    def pos(y):
        return math.log10(y) if logy else y

    lo = min(pos(y) for _, _, y in ends)
    hi = max(pos(y) for _, _, y in ends)
    min_sep = max((hi - lo), 1e-9) * 0.07 or 1.0
    placed = []
    for topo, x, y in sorted(ends, key=lambda e: pos(e[2])):
        p = pos(y)
        if placed and p - placed[-1] < min_sep:
            p = placed[-1] + min_sep
        placed.append(p)
        ax.annotate(f" {topo}", (x, 10 ** p if logy else p),
                    color=SLOT[topo], fontsize=8, va="center")
    ax.legend(frameon=False, fontsize=8, labelcolor=INK)


def load_rows(path):
    with open(path) as fh:
        return list(csv.DictReader(fh))


def main():
    # --- TPU wall-clock curves -------------------------------------------
    rows = load_rows(os.path.join(ART, "curves_tpu_v5e1.csv"))
    fig, axes = plt.subplots(1, 2, figsize=(10, 4))
    fig.patch.set_facecolor(SURFACE)
    for ax, algo, ref_note in (
        (axes[0], "gossip", "Report.pdf p.1: 250-3700 ms"),
        (axes[1], "push-sum", "Report.pdf p.2: 500-8400 ms"),
    ):
        series = defaultdict(list)
        for r in rows:
            if r["algorithm"] == algo:
                series[r["topology"]].append(
                    (int(r["nodes_requested"]), float(r["wall_ms"]))
                )
        _plot_series(ax, series)
        _style_axis(ax, f"{algo} — TPU v5e (1 chip)", "wall-clock ms")
        ax.set_ylim(bottom=0)
        ax.annotate(f"F# reference range: {ref_note.split(': ')[1]}",
                    xy=(0.02, 0.02), xycoords="axes fraction",
                    color=MUTED, fontsize=8)
    fig.suptitle("Time to convergence vs node count (dispatch-bound flat "
                 "~200 ms; reference is 250-8400 ms)", color=INK, fontsize=10)
    fig.tight_layout()
    out1 = os.path.join(ART, "curves_plot.png")
    fig.savefig(out1, dpi=150, facecolor=SURFACE)

    # --- oracle shape curves ---------------------------------------------
    rows = load_rows(os.path.join(ART, "oracle_curves.csv"))
    fig, axes = plt.subplots(1, 2, figsize=(10, 4))
    fig.patch.set_facecolor(SURFACE)
    for ax, col, title in (
        (axes[0], "gossip_events_median", "gossip — oracle event count"),
        (axes[1], "pushsum_hops_median", "push-sum — oracle walk hops"),
    ):
        series = defaultdict(list)
        for r in rows:
            series[r["topology"]].append(
                (int(r["nodes_requested"]), int(r[col]))
            )
        _plot_series(ax, series, logy=True)
        _style_axis(ax, title, "events (log)")
    fig.suptitle("Reference actor-semantics shapes via the async oracle "
                 "(full < imp3D ≤ 3D ≪ line — matches Report.pdf)",
                 color=INK, fontsize=10)
    fig.tight_layout()
    out2 = os.path.join(ART, "oracle_plot.png")
    fig.savefig(out2, dpi=150, facecolor=SURFACE)

    # --- calibrated predicted-vs-published overlay ------------------------
    # one events/ms constant per algorithm (anchor full@1000), applied to
    # every point; published values exist only at n=1000, drawn as hollow
    # diamonds on the predicted curves
    fig, axes = plt.subplots(1, 2, figsize=(10, 4))
    fig.patch.set_facecolor(SURFACE)
    for ax, pred_col, pub_col, title in (
        (axes[0], "predicted_gossip_ms", "published_gossip_ms",
         "gossip — predicted reference ms"),
        (axes[1], "predicted_pushsum_ms", "published_pushsum_ms",
         "push-sum — predicted reference ms"),
    ):
        series = defaultdict(list)
        published = []
        for r in rows:
            if r.get(pred_col):
                series[r["topology"]].append(
                    (int(r["nodes_requested"]), float(r[pred_col]))
                )
            if r.get(pub_col):
                published.append(
                    (r["topology"], int(r["nodes_requested"]),
                     float(r[pub_col]))
                )
        _plot_series(ax, series, logy=True)
        for topo, x, y in published:
            ax.plot([x], [y], marker="D", markersize=7, mew=1.5,
                    mfc="none", mec=SLOT[topo], linestyle="none")
        _style_axis(ax, title, "predicted ms (log)")
    fig.suptitle("Oracle counts x fitted events/ms (anchor full@1000) vs "
                 "Report.pdf published points (diamonds)",
                 color=INK, fontsize=10)
    fig.tight_layout()
    out3 = os.path.join(ART, "oracle_calibration_plot.png")
    fig.savefig(out3, dpi=150, facecolor=SURFACE)
    print(out1)
    print(out2)
    print(out3)


if __name__ == "__main__":
    main()
