"""Attribute per-round wall-clock of the 1M-node gossip chunk (VERDICT #4).

Decomposes one bulk-synchronous gossip round at BENCH scale into its
kernels and measures while_loop / predicate overhead, printing a
ms-per-round table.

Measurement notes (both matter on this image):
  * ``jax.block_until_ready`` does NOT reliably block through the remote
    "axon" TPU tunnel — every timing here syncs by ``device_get`` of a
    scalar reduction of the result instead (a data dependency the tunnel
    cannot skip).
  * the FIRST execution of a compiled program costs seconds extra
    (program load + input upload over the tunnel); all timings warm up
    once and report min-of-repeats.

Usage:  python experiments/profile_round.py [--nodes 1000000] [--rounds 64]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from gossipprotocol_tpu import RunConfig, build_topology
from gossipprotocol_tpu.engine.driver import build_protocol, make_chunk_runner
from gossipprotocol_tpu.protocols.sampling import device_topology, sample_neighbors


def timed(fn, repeats=5):
    """min-of-repeats seconds; fn must itself sync (device_get a scalar)."""
    fn()  # warmup: compile + program load + input upload
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def sync(x):
    """Force full execution: fetch a scalar that depends on every element."""
    return float(jax.device_get(jnp.sum(jnp.asarray(x, jnp.float32))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1_000_000)
    ap.add_argument("--rounds", type=int, default=64)
    ap.add_argument("--profile-dir", type=str, default=None)
    args = ap.parse_args()

    topo = build_topology("imp3D", args.nodes, seed=0)
    n = topo.num_nodes
    # huge threshold: the loop must not converge inside the measured chunk
    cfg = RunConfig(algorithm="gossip", seed=0, threshold=1_000_000_000)
    state0, core, done_fn, extra, _ = build_protocol(topo, cfg)
    nbrs = device_topology(topo)
    key = jax.random.key(0)
    R = args.rounds
    print(f"nodes={n} rounds/loop={R} backend={jax.default_backend()}")

    # mid-run state: everyone has heard, so spreader mask and scatter work
    # match the steady state the bench spends its time in
    state0 = state0._replace(counts=jnp.ones_like(state0.counts))

    # (a) the real chunk runner: while_loop with the done predicate in cond
    runner = make_chunk_runner(core, done_fn, extra)
    compiled = runner.lower(
        jax.tree.map(jnp.array, state0), nbrs, key, jnp.int32(0)
    ).compile()

    def run_chunk():
        st = jax.tree.map(jnp.array, state0)  # fresh (runner donates)
        out, stats = compiled(st, nbrs, key, jnp.int32(R))
        assert int(jax.device_get(stats["round"])) == R
        return sync(out.counts)

    t_chunk = timed(run_chunk)

    # (b) fori_loop, fixed trip count, no predicate in any cond
    @jax.jit
    def chunk_fori(st, nbrs, key):
        def body(_, s):
            return core(s, nbrs, key)
        return jax.lax.fori_loop(0, R, body, st)

    t_fori = timed(lambda: sync(chunk_fori(state0, nbrs, key).counts))

    # (c) kernel decomposition (one round's pieces, jitted separately).
    # Sampling is measured for BOTH backends — the engine defaults to the
    # dense table on bounded-degree graphs; CSR is what power-law gets.
    nbrs_dense = device_topology(topo, dense=True)
    nbrs_csr = device_topology(topo, dense=False)

    @jax.jit
    def k_sample(st, nbrs, key):
        k = jax.random.fold_in(key, st.round)
        return sample_neighbors(nbrs, n, k)[0]

    @jax.jit
    def k_scatter(v, t):
        return jax.ops.segment_sum(v, t, num_segments=n)

    @jax.jit
    def k_predicate(st):
        return jnp.all(st.converged | ~st.alive)

    @jax.jit
    def k_round(st, nbrs, key):
        return core(st, nbrs, key)

    targets = jax.device_get(k_sample(state0, nbrs_dense, key))
    targets = jnp.asarray(targets)
    ones = jnp.ones(n, state0.counts.dtype)
    t_dense = timed(lambda: sync(k_sample(state0, nbrs_dense, key)))
    t_csr = timed(lambda: sync(k_sample(state0, nbrs_csr, key)))
    t_scatter = timed(lambda: sync(k_scatter(ones, targets)))
    t_pred = timed(lambda: sync(k_predicate(state0)))
    t_round1 = timed(lambda: sync(k_round(state0, nbrs, key).counts))

    ms = lambda s: s * 1e3  # noqa: E731
    print(f"chunk while_loop   : {ms(t_chunk)/R:8.2f} ms/round  ({ms(t_chunk):.1f} ms total)")
    print(f"chunk fori_loop    : {ms(t_fori)/R:8.2f} ms/round  ({ms(t_fori):.1f} ms total)")
    print(f"  -> loop/predicate overhead: {ms(t_chunk - t_fori)/R:.2f} ms/round")
    print(f"single jitted round: {ms(t_round1):8.2f} ms (incl. one dispatch+fetch)")
    print("  NOTE: the per-kernel rows below each include one ~100 ms tunnel")
    print("  dispatch+fetch; subtract the predicate row as the RTT baseline")
    print(f"  sample, dense one-hot (engine default): {ms(t_dense):8.2f} ms")
    print(f"  sample, CSR gather (power-law path)   : {ms(t_csr):8.2f} ms")
    print(f"  scatter-add (segment_sum)             : {ms(t_scatter):8.2f} ms")
    print(f"  predicate (all-reduce; ~= bare RTT)   : {ms(t_pred):8.2f} ms")

    if args.profile_dir:
        with jax.profiler.trace(args.profile_dir):
            run_chunk()
        print(f"trace written to {args.profile_dir}")


if __name__ == "__main__":
    main()
