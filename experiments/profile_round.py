"""Attribute per-round wall-clock of the 1M-node gossip chunk (VERDICT #4).

Decomposes one bulk-synchronous gossip round at BENCH scale into its
kernels and measures while_loop / predicate overhead, printing a
ms-per-round table.

Measurement notes (both matter on this image):
  * ``jax.block_until_ready`` does NOT reliably block through the remote
    "axon" TPU tunnel — every timing here syncs by ``device_get`` of a
    scalar reduction of the result instead (a data dependency the tunnel
    cannot skip).
  * the FIRST execution of a compiled program costs seconds extra
    (program load + input upload over the tunnel); all timings warm up
    once and report min-of-repeats.

Usage:  python experiments/profile_round.py [--nodes 1000000] [--rounds 64]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from gossipprotocol_tpu import RunConfig, build_topology
from gossipprotocol_tpu.engine.driver import (
    build_protocol, device_arrays, make_chunk_runner,
)
from gossipprotocol_tpu.protocols.sampling import device_topology, sample_neighbors

# v5e HBM2 peak (the chip this repo's BENCH numbers come from); override
# with --hbm-gbps for other parts
V5E_HBM_GBPS = 819.0


def timed(fn, repeats=5):
    """min-of-repeats seconds; fn must itself sync (device_get a scalar)."""
    fn()  # warmup: compile + program load + input upload
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def sync(x):
    """Force full execution: fetch a scalar that depends on every element."""
    return float(jax.device_get(jnp.sum(jnp.asarray(x, jnp.float32))))


def min_bytes_per_round(topo, algorithm: str, fanout: str = "one",
                        delivery: str = "scatter") -> int:
    """Lower-bound HBM traffic of one round: every persistent array read
    once and every output written once; intermediates assumed perfectly
    fused. int32 counts/ids, float32 mass, 1-byte bools.

    This is the numerator of the roofline: achieved_BW = min_bytes /
    measured_time. For the scatter deliveries, achieved ≪ peak is
    expected — a random int32 scatter-add (`segment_sum` with
    uniform-random segment ids) lowers to serialized read-modify-write
    traffic, not streaming; the model quantifies *how far* from streaming
    the round runs. The gather-inverted gossip delivery
    (``delivery="invert"``, the engine default in its steady state) is
    pure elementwise streaming over the int32 table + two int8 inversion
    tables, so its achieved fraction is the honest ceiling story.
    """
    n = topo.num_nodes
    maxd = 0 if topo.implicit_full else int(topo.degree.max())
    e = 0 if topo.implicit_full else int(topo.indices.size)
    if algorithm == "gossip":
        if delivery == "invert":
            # table 4·maxd + rev/deg_nbr int8 2·maxd + degree 4 |
            # counts r/w 8 | converged r/w 2 | alive read 1
            return n * (6 * maxd + 4 + 8 + 2 + 1)
        # table read 4·maxd + degree 4 | counts r/w 8 | converged r/w 2 |
        # alive read 1 | hits (scatter out) 4
        return n * (4 * maxd + 4 + 8 + 2 + 1 + 4)
    if fanout == "one":
        # table read 4·maxd + degree 4 | s,w,ratio r/w 24 | streak r/w 8 |
        # converged r/w 2 | alive 1 | two scatter outputs 8
        return n * (4 * maxd + 4 + 24 + 8 + 2 + 1 + 8)
    # diffusion: per-edge src+dst ids 8 + two share streams (read at the
    # gather, accumulated at the scatter) 16 | per-node state r/w as above
    # minus the sampled table
    return e * (8 + 16) + n * (4 + 24 + 8 + 2 + 1 + 8)


def time_protocol_round(
    topo, cfg: RunConfig, rounds: int, repeats: int = 5
) -> float:
    """Seconds per round of the real chunk runner (convergence disabled so
    the loop always runs the full ``rounds``), min-of-repeats, warmed."""
    state0, core, done_fn, extra, _ = build_protocol(topo, cfg)
    if cfg.algorithm == "gossip":
        # steady state: everyone heard -> spreader mask and scatter work
        # match where the bench spends its time
        state0 = state0._replace(counts=jnp.ones_like(state0.counts))
    nbrs = device_arrays(topo, cfg)
    key = jax.random.key(0)
    runner = make_chunk_runner(core, done_fn, extra)
    compiled = runner.lower(
        jax.tree.map(jnp.array, state0), nbrs, key, jnp.int32(0)
    ).compile()

    # full-trip check once, outside the timed closure: a second blocking
    # fetch per repeat would add ~100 ms of tunnel RTT to every timing
    _, stats = compiled(
        jax.tree.map(jnp.array, state0), nbrs, key, jnp.int32(rounds)
    )
    assert int(jax.device_get(stats["round"])) == rounds

    def run():
        st = jax.tree.map(jnp.array, state0)
        out, _ = compiled(st, nbrs, key, jnp.int32(rounds))
        return sync(out[0])  # counts (gossip) / s (push-sum)

    return timed(run, repeats) / rounds


def roofline(nodes: int, rounds: int, hbm_gbps: float) -> None:
    """ms/round, minimum bytes moved, achieved GB/s, and % of HBM peak for
    the round types at BENCH scale (VERDICT r2 missing #2).

    The gossip steady state (counts=1 everywhere in
    ``time_protocol_round``) takes the delivery the engine would take:
    gather-inverted by default, scatter with ``GOSSIP_TPU_INVERT=0`` —
    both rows are measured so the byte model matches what actually ran.
    """
    print(f"\nroofline @ n={nodes} (peak {hbm_gbps:.0f} GB/s):")
    print(f"{'round type':34s} {'ms/round':>9s} {'MB moved':>9s} "
          f"{'GB/s':>7s} {'% HBM':>6s}")
    configs = [
        ("gossip (imp3D, dense+invert)", "imp3D", RunConfig(
            algorithm="gossip", seed=0, threshold=2**30), "one",
         "invert", "1"),
        ("gossip (imp3D, dense+scatter)", "imp3D", RunConfig(
            algorithm="gossip", seed=0, threshold=2**30), "one",
         "scatter", "0"),
        ("push-sum (ER8, dense+scatter)", "erdos_renyi", RunConfig(
            algorithm="push-sum", seed=0, streak_target=2**30), "one",
         "scatter", None),
        ("push-sum diffusion (powerlaw)", "powerlaw", RunConfig(
            algorithm="push-sum", fanout="all", seed=0,
            streak_target=2**30), "all", "scatter", None),
    ]
    for label, kind, cfg, fanout, delivery, invert_env in configs:
        # GOSSIP_TPU_INVERT is read when build_protocol compiles the core,
        # so it selects which gossip delivery this row measures
        prev = os.environ.get("GOSSIP_TPU_INVERT")
        if invert_env is not None:
            os.environ["GOSSIP_TPU_INVERT"] = invert_env
        # diffusion walks every edge (~8N): at 10M that is ~5.4 s/round,
        # and a >2-minute single dispatch trips the remote watchdog
        # (observed: TPU worker crash) — cap this row's trip count
        big_diffusion = fanout == "all" and nodes > 2_000_000
        r = min(rounds, 8) if big_diffusion else rounds
        try:
            topo = build_topology(kind, nodes, seed=0)
            # 2 repeats: each 8-round diffusion dispatch is ~43 s at 10M;
            # min-of-5 would push the row alone past 5 minutes
            t = time_protocol_round(
                topo, cfg, r, repeats=2 if big_diffusion else 5
            )
        finally:
            if invert_env is not None:
                if prev is None:
                    os.environ.pop("GOSSIP_TPU_INVERT", None)
                else:
                    os.environ["GOSSIP_TPU_INVERT"] = prev
        b = min_bytes_per_round(topo, cfg.algorithm, fanout, delivery)
        gbs = b / t / 1e9
        print(f"{label:34s} {t*1e3:9.2f} {b/1e6:9.1f} {gbs:7.1f} "
              f"{100*gbs/hbm_gbps:6.2f}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1_000_000)
    ap.add_argument("--rounds", type=int, default=64)
    ap.add_argument("--profile-dir", type=str, default=None)
    ap.add_argument("--hbm-gbps", type=float, default=V5E_HBM_GBPS)
    ap.add_argument("--roofline-only", action="store_true")
    args = ap.parse_args()

    if args.roofline_only:
        roofline(args.nodes, args.rounds, args.hbm_gbps)
        return

    topo = build_topology("imp3D", args.nodes, seed=0)
    n = topo.num_nodes
    # huge threshold: the loop must not converge inside the measured chunk
    cfg = RunConfig(algorithm="gossip", seed=0, threshold=1_000_000_000)
    state0, core, done_fn, extra, _ = build_protocol(topo, cfg)
    nbrs = device_arrays(topo, cfg)  # InvertedDense when the default
    # gather-inverted delivery is compiled in (GOSSIP_TPU_INVERT)
    key = jax.random.key(0)
    R = args.rounds
    print(f"nodes={n} rounds/loop={R} backend={jax.default_backend()}")

    # mid-run state: everyone has heard, so spreader mask and scatter work
    # match the steady state the bench spends its time in
    state0 = state0._replace(counts=jnp.ones_like(state0.counts))

    # (a) the real chunk runner: while_loop with the done predicate in cond
    runner = make_chunk_runner(core, done_fn, extra)
    compiled = runner.lower(
        jax.tree.map(jnp.array, state0), nbrs, key, jnp.int32(0)
    ).compile()

    def run_chunk():
        st = jax.tree.map(jnp.array, state0)  # fresh (runner donates)
        out, stats = compiled(st, nbrs, key, jnp.int32(R))
        assert int(jax.device_get(stats["round"])) == R
        return sync(out.counts)

    t_chunk = timed(run_chunk)

    # (b) fori_loop, fixed trip count, no predicate in any cond
    @jax.jit
    def chunk_fori(st, nbrs, key):
        def body(_, s):
            return core(s, nbrs, key)
        return jax.lax.fori_loop(0, R, body, st)

    t_fori = timed(lambda: sync(chunk_fori(state0, nbrs, key).counts))

    # (c) kernel decomposition (one round's pieces, jitted separately).
    # Sampling is measured for BOTH backends — the engine defaults to the
    # dense table on bounded-degree graphs; CSR is what power-law gets.
    nbrs_dense = device_topology(topo, dense=True)
    nbrs_csr = device_topology(topo, dense=False)

    @jax.jit
    def k_sample(st, nbrs, key):
        k = jax.random.fold_in(key, st.round)
        return sample_neighbors(nbrs, n, k)[0]

    @jax.jit
    def k_scatter(v, t):
        return jax.ops.segment_sum(v, t, num_segments=n)

    @jax.jit
    def k_predicate(st):
        return jnp.all(st.converged | ~st.alive)

    @jax.jit
    def k_round(st, nbrs, key):
        return core(st, nbrs, key)

    targets = jax.device_get(k_sample(state0, nbrs_dense, key))
    targets = jnp.asarray(targets)
    ones = jnp.ones(n, state0.counts.dtype)
    t_dense = timed(lambda: sync(k_sample(state0, nbrs_dense, key)))
    t_csr = timed(lambda: sync(k_sample(state0, nbrs_csr, key)))
    t_scatter = timed(lambda: sync(k_scatter(ones, targets)))
    t_pred = timed(lambda: sync(k_predicate(state0)))
    t_round1 = timed(lambda: sync(k_round(state0, nbrs, key).counts))

    ms = lambda s: s * 1e3  # noqa: E731
    print(f"chunk while_loop   : {ms(t_chunk)/R:8.2f} ms/round  ({ms(t_chunk):.1f} ms total)")
    print(f"chunk fori_loop    : {ms(t_fori)/R:8.2f} ms/round  ({ms(t_fori):.1f} ms total)")
    print(f"  -> loop/predicate overhead: {ms(t_chunk - t_fori)/R:.2f} ms/round")
    print(f"single jitted round: {ms(t_round1):8.2f} ms (incl. one dispatch+fetch)")
    print("  NOTE: the per-kernel rows below each include one ~100 ms tunnel")
    print("  dispatch+fetch; subtract the predicate row as the RTT baseline")
    print(f"  sample, dense one-hot (engine default): {ms(t_dense):8.2f} ms")
    print(f"  sample, CSR gather (power-law path)   : {ms(t_csr):8.2f} ms")
    print(f"  scatter-add (segment_sum)             : {ms(t_scatter):8.2f} ms")
    print(f"  predicate (all-reduce; ~= bare RTT)   : {ms(t_pred):8.2f} ms")

    roofline(args.nodes, args.rounds, args.hbm_gbps)

    if args.profile_dir:
        with jax.profiler.trace(args.profile_dir):
            run_chunk()
        print(f"trace written to {args.profile_dir}")


if __name__ == "__main__":
    main()
